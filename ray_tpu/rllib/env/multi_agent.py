"""Multi-agent environments and rollout collection.

Reference: `rllib/env/multi_agent_env.py:31` (dict-keyed step/reset API),
`rllib/env/multi_agent_env_runner.py` (per-agent episode bookkeeping,
module routing via the policy mapping fn) and the multi-agent RLModule
container (`rllib/core/rl_module/multi_rl_module.py`). TPU-first shape:
each policy module stays a pure-functional Flax RLModule; the runner
groups the agents that share a module and does ONE batched forward per
module per env step (instead of the reference's per-agent passes), so
rollout compute stays vectorised however many agents the env has.

Design decision vs the reference: policies are trained as independent
modules (shared policies = many agents mapped onto one module). The
reference couples modules through a summed loss inside one Learner —
that only matters for shared encoders, which the flat RLModuleSpec
doesn't model; independent per-module Learners keep every module's
update a single jitted program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import Columns, RLModuleSpec
from ray_tpu.rllib.env.env_runner import Episode
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager

AgentID = str
ModuleID = str


class MultiAgentEnv:
    """Dict-keyed environment: every step consumes an action per *live*
    agent and returns per-agent obs/rewards/terms/truncs plus the
    "__all__" episode-done flag (reference `multi_agent_env.py:66`).

    Subclasses define `possible_agents`, `observation_spaces`,
    `action_spaces` (dicts keyed by agent id) and the two methods below.
    Agents may appear/disappear between steps; only agents present in
    the returned obs dict act next step.
    """

    possible_agents: List[AgentID] = []
    observation_spaces: Dict[AgentID, Any] = {}
    action_spaces: Dict[AgentID, Any] = {}

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[AgentID, np.ndarray], Dict]:
        raise NotImplementedError

    def step(self, actions: Dict[AgentID, Any]) -> Tuple[
            Dict[AgentID, np.ndarray], Dict[AgentID, float],
            Dict[AgentID, bool], Dict[AgentID, bool], Dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MultiAgentEnvRunner:
    """Collects per-agent episode fragments from one MultiAgentEnv.

    Episodes are tagged with the module that produced them; `sample`
    returns {module_id: [Episode, ...]} so each module's connector/GAE/
    learner path is exactly the single-agent one.
    """

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 specs: Dict[ModuleID, RLModuleSpec],
                 policy_mapping_fn: Callable[[AgentID], ModuleID],
                 seed: int = 0,
                 explore_config: Optional[Dict[str, Any]] = None):
        import jax

        self._env = env_creator()
        self._mapping = policy_mapping_fn
        self.modules = {mid: spec.build() for mid, spec in specs.items()}
        self._params: Dict[ModuleID, Any] = {}
        self._rng = jax.random.PRNGKey(seed)
        self._explore = dict(explore_config or {})
        self._seed = seed
        self._obs, _ = self._env.reset(seed=seed)
        self._open: Dict[AgentID, Episode] = {}
        self._completed_returns: List[float] = []  # env-level (summed)
        self._episode_reward = 0.0

    def set_weights(self, weights: Dict[ModuleID, Any]) -> None:
        import jax
        import jax.numpy as jnp
        self._params = {
            mid: jax.tree_util.tree_map(jnp.asarray, w)
            for mid, w in weights.items()
        }

    def set_explore_config(self, explore_config: Dict[str, Any]) -> None:
        self._explore = dict(explore_config)

    def _module_of(self, agent: AgentID) -> ModuleID:
        return self._mapping(agent)

    def _forward(self, agents: List[AgentID], explore: bool):
        """One batched forward per module covering its live agents."""
        import jax

        by_module: Dict[ModuleID, List[AgentID]] = {}
        for a in agents:
            by_module.setdefault(self._module_of(a), []).append(a)
        acts: Dict[AgentID, Any] = {}
        logps: Dict[AgentID, float] = {}
        vfs: Dict[AgentID, float] = {}
        for mid, group in by_module.items():
            obs = np.stack([np.asarray(self._obs[a], np.float32).ravel()
                            for a in group])
            self._rng, key = jax.random.split(self._rng)
            mod = self.modules[mid]
            if explore:
                fwd = mod.forward_exploration(self._params[mid], obs, key,
                                              **self._explore)
            else:
                fwd = mod.forward_inference(self._params[mid], obs)
            actions = np.asarray(fwd["actions"])
            lp = np.asarray(fwd.get(Columns.ACTION_LOGP,
                                    np.zeros(len(group))))
            vf = np.asarray(fwd.get(Columns.VF_PREDS,
                                    np.zeros(len(group))))
            for i, a in enumerate(group):
                act = actions[i]
                acts[a] = (int(act) if np.ndim(act) == 0
                           else np.asarray(act, np.float32))
                logps[a] = float(lp[i])
                vfs[a] = float(vf[i])
        return acts, logps, vfs

    def sample(self, num_steps: int = 200, explore: bool = True
               ) -> Dict[ModuleID, List[Episode]]:
        assert self._params, "set_weights first"
        out: Dict[ModuleID, List[Episode]] = {
            mid: [] for mid in self.modules}
        steps = 0
        while steps < num_steps:
            agents = list(self._obs.keys())
            acts, logps, vfs = self._forward(agents, explore)
            next_obs, rewards, terms, truncs, _ = self._env.step(acts)
            for a in agents:
                ep = self._open.setdefault(a, Episode())
                ep.obs.append(np.asarray(self._obs[a], np.float32).ravel())
                ep.actions.append(acts[a])
                ep.rewards.append(float(rewards.get(a, 0.0)))
                ep.logps.append(logps[a])
                ep.vf_preds.append(vfs[a])
                self._episode_reward += float(rewards.get(a, 0.0))
            done_all = terms.get("__all__", False) or \
                truncs.get("__all__", False)
            for a in agents:
                a_done = terms.get(a, False) or truncs.get(a, False)
                # an agent may also vanish from the obs dict with no
                # term/trunc flag (it left the episode) — close its
                # fragment rather than stranding it in self._open
                vanished = not done_all and not a_done and a not in next_obs
                if a_done or done_all or vanished:
                    ep = self._open.pop(a, None)
                    if ep is not None and ep.length:
                        ep.terminated = bool(
                            terms.get(a, False) or terms.get("__all__",
                                                             False))
                        ep.truncated = not ep.terminated
                        if a in next_obs:
                            ep.last_obs = np.asarray(
                                next_obs[a], np.float32).ravel()
                        out[self._module_of(a)].append(ep)
            steps += len(agents)
            if done_all:
                # flush fragments of agents that were already absent
                # this step, then start a fresh episode
                for a, ep in self._open.items():
                    if ep.length:
                        ep.truncated = True
                        out[self._module_of(a)].append(ep)
                self._completed_returns.append(self._episode_reward)
                self._episode_reward = 0.0
                self._seed += 1
                self._obs, _ = self._env.reset(seed=self._seed)
                self._open.clear()
            else:
                self._obs = next_obs
        # flush open fragments (bootstrapped by GAE via last_obs)
        for a, ep in list(self._open.items()):
            if ep.length:
                ep.last_obs = np.asarray(self._obs[a], np.float32).ravel()
                out[self._module_of(a)].append(ep)
                self._open[a] = Episode()
        return out

    def get_metrics(self) -> Dict[str, Any]:
        recent = self._completed_returns[-100:]
        return {
            "episode_return_mean": (float(np.mean(recent))
                                    if recent else None),
            "num_episodes": len(self._completed_returns),
        }

    def ping(self) -> bool:
        return True


class MultiAgentEnvRunnerGroup:
    """Fleet of multi-agent runners; mirrors EnvRunnerGroup (local mode
    at num_env_runners=0, fault-tolerant actor fleet otherwise)."""

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 specs: Dict[ModuleID, RLModuleSpec],
                 policy_mapping_fn: Callable[[AgentID], ModuleID],
                 num_env_runners: int = 0, seed: int = 0,
                 explore_config: Optional[Dict[str, Any]] = None):
        self.num_env_runners = num_env_runners
        if num_env_runners == 0:
            self.local_runner = MultiAgentEnvRunner(
                env_creator, specs, policy_mapping_fn, seed,
                explore_config)
            self.manager = None
        else:
            self.local_runner = None
            cls = ray_tpu.remote(MultiAgentEnvRunner)
            actors = [
                cls.remote(env_creator, specs, policy_mapping_fn,
                           seed + 1000 * (i + 1), explore_config)
                for i in range(num_env_runners)
            ]
            restart = (lambda i: cls.remote(
                env_creator, specs, policy_mapping_fn,
                seed + 1000 * (i + 1), explore_config))
            self.manager = FaultTolerantActorManager(actors, restart)

    def sync_weights(self, weights: Dict[ModuleID, Any]) -> None:
        if self.local_runner is not None:
            self.local_runner.set_weights(weights)
        else:
            self.manager.foreach(lambda a: a.set_weights.remote(weights))

    def sample(self, num_steps: int, explore: bool = True
               ) -> Dict[ModuleID, List[Episode]]:
        if self.local_runner is not None:
            return self.local_runner.sample(num_steps, explore)
        per = max(1, num_steps // max(1, self.manager.num_healthy()))
        results = self.manager.foreach(
            lambda a: a.sample.remote(per, explore), timeout=600)
        out: Dict[ModuleID, List[Episode]] = {}
        for res in results:
            for mid, eps in res.items():
                out.setdefault(mid, []).extend(eps)
        return out

    def get_metrics(self) -> List[Dict[str, Any]]:
        if self.local_runner is not None:
            return [self.local_runner.get_metrics()]
        return self.manager.foreach(lambda a: a.get_metrics.remote())

    def stop(self) -> None:
        if self.manager is not None:
            self.manager.stop()
        elif self.local_runner is not None:
            self.local_runner._env.close()
