"""EnvRunners: rollout collection actors.

Reference: `rllib/env/env_runner.py:15` (ABC),
`single_agent_env_runner.py:49` (gymnasium vector envs + RLModule
forward_exploration through connector pipelines),
`env_runner_group.py:66` (the fault-tolerant fleet). The runner holds
numpy weights; the forward pass runs on the runner's local device (CPU for
sim envs — the learner's TPU mesh stays dedicated to updates).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import Columns, RLModuleSpec
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager


class Episode:
    """One (possibly truncated) episode fragment of columnar data."""

    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.rewards: List[float] = []
        self.logps: List[float] = []
        self.vf_preds: List[float] = []
        self.terminated = False
        self.truncated = False
        self.last_obs: Optional[np.ndarray] = None

    @property
    def length(self) -> int:
        return len(self.actions)

    @property
    def total_reward(self) -> float:
        return float(sum(self.rewards))


class SingleAgentEnvRunner:
    """Steps N vectorized gymnasium envs with the current module weights."""

    def __init__(self, env_creator: Callable, spec: RLModuleSpec,
                 num_envs: int = 1, seed: int = 0,
                 explore_config: Optional[Dict[str, Any]] = None):
        import gymnasium as gym
        import jax

        self._envs = gym.vector.SyncVectorEnv(
            [lambda i=i: env_creator() for i in range(num_envs)])
        self.num_envs = num_envs
        self.module = spec.build()
        self._params = None
        self._rng = jax.random.PRNGKey(seed)
        self._explore = dict(explore_config or {})
        self._obs, _ = self._envs.reset(seed=seed)
        self._open = [Episode() for _ in range(num_envs)]
        self._completed_rewards: List[float] = []

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp
        self._params = jax.tree_util.tree_map(jnp.asarray, weights)

    def set_explore_config(self, explore_config: Dict[str, Any]) -> None:
        """Update exploration kwargs (e.g. DQN's decayed epsilon) passed
        to the module's forward_exploration on subsequent samples."""
        self._explore = dict(explore_config)

    def sample(self, num_steps: int = 200,
               explore: bool = True) -> List[Episode]:
        """Collect ≥num_steps env steps; returns closed + open fragments."""
        import jax
        assert self._params is not None, "set_weights first"
        episodes: List[Episode] = []
        steps = 0
        while steps < num_steps:
            self._rng, key = jax.random.split(self._rng)
            obs = np.asarray(self._obs, np.float32)
            if explore:
                fwd = self.module.forward_exploration(
                    self._params, obs, key, **self._explore)
            else:
                fwd = self.module.forward_inference(self._params, obs)
            actions = np.asarray(fwd["actions"])
            logps = np.asarray(fwd.get(Columns.ACTION_LOGP,
                                       np.zeros(self.num_envs)))
            vfs = np.asarray(fwd.get(Columns.VF_PREDS,
                                     np.zeros(self.num_envs)))
            next_obs, rewards, terms, truncs, _ = self._envs.step(actions)
            for i in range(self.num_envs):
                ep = self._open[i]
                ep.obs.append(obs[i])
                # discrete -> python int; continuous (Box) -> float vec
                a = actions[i]
                ep.actions.append(
                    int(a) if np.ndim(a) == 0 else
                    np.asarray(a, np.float32))
                ep.rewards.append(float(rewards[i]))
                ep.logps.append(float(logps[i]))
                ep.vf_preds.append(float(vfs[i]))
                if terms[i] or truncs[i]:
                    ep.terminated = bool(terms[i])
                    ep.truncated = bool(truncs[i])
                    # vector envs auto-reset; final_obs only matters for
                    # bootstrapping truncated episodes
                    ep.last_obs = np.asarray(next_obs[i], np.float32)
                    episodes.append(ep)
                    self._completed_rewards.append(ep.total_reward)
                    self._open[i] = Episode()
            self._obs = next_obs
            steps += self.num_envs
        # flush open fragments (bootstrapped by the learner connector)
        for i in range(self.num_envs):
            ep = self._open[i]
            if ep.length:
                ep.last_obs = np.asarray(self._obs[i], np.float32)
                episodes.append(ep)
                self._open[i] = Episode()
        return episodes

    def get_metrics(self) -> Dict[str, Any]:
        recent = self._completed_rewards[-100:]
        return {
            "episode_return_mean": (float(np.mean(recent))
                                    if recent else None),
            "num_episodes": len(self._completed_rewards),
        }

    def ping(self) -> bool:
        return True


class EnvRunnerGroup:
    """Fleet of env-runner actors with fault tolerance.

    Reference: `rllib/env/env_runner_group.py:66` — remote runners managed
    by `FaultTolerantActorManager`; `num_env_runners=0` runs one local
    runner in-process (the reference's local-worker mode).
    """

    def __init__(self, env_creator: Callable, spec: RLModuleSpec,
                 num_env_runners: int = 0, num_envs_per_runner: int = 1,
                 seed: int = 0,
                 explore_config: Optional[Dict[str, Any]] = None):
        self.num_env_runners = num_env_runners
        if num_env_runners == 0:
            self.local_runner = SingleAgentEnvRunner(
                env_creator, spec, num_envs_per_runner, seed,
                explore_config)
            self.manager = None
        else:
            self.local_runner = None
            cls = ray_tpu.remote(SingleAgentEnvRunner)
            actors = [
                cls.remote(env_creator, spec, num_envs_per_runner,
                           seed + 1000 * (i + 1), explore_config)
                for i in range(num_env_runners)
            ]
            restart = (lambda i: cls.remote(
                env_creator, spec, num_envs_per_runner,
                seed + 1000 * (i + 1), explore_config))
            self.manager = FaultTolerantActorManager(actors, restart)

    def sync_weights(self, weights) -> None:
        if self.local_runner is not None:
            self.local_runner.set_weights(weights)
        else:
            self.manager.foreach(lambda a: a.set_weights.remote(weights))

    def set_explore_config(self, explore_config: Dict[str, Any]) -> None:
        if self.local_runner is not None:
            self.local_runner.set_explore_config(explore_config)
        else:
            self.manager.foreach(
                lambda a: a.set_explore_config.remote(explore_config))

    def sample(self, num_steps: int,
               explore: bool = True) -> List[Episode]:
        if self.local_runner is not None:
            return self.local_runner.sample(num_steps, explore)
        per = max(1, num_steps // max(1, self.manager.num_healthy()))
        results = self.manager.foreach(
            lambda a: a.sample.remote(per, explore), timeout=600)
        out: List[Episode] = []
        for eps in results:
            out.extend(eps)
        return out

    def get_metrics(self) -> List[Dict[str, Any]]:
        if self.local_runner is not None:
            return [self.local_runner.get_metrics()]
        return self.manager.foreach(lambda a: a.get_metrics.remote())

    def stop(self) -> None:
        if self.manager is not None:
            self.manager.stop()
