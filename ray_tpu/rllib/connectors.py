"""ConnectorV2 pipelines — episodes → train batch.

Reference: `rllib/connectors/connector_v2.py:18` and the learner-pipeline
GAE connector (`rllib/connectors/learner/
general_advantage_estimation.py`). Kept as plain composable callables:
each connector takes and returns the (episodes, batch) pair.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.env.env_runner import Episode

Batch = Dict[str, np.ndarray]


class ConnectorPipeline:
    def __init__(self, connectors: List[Callable]):
        self.connectors = list(connectors)

    def __call__(self, episodes: List[Episode], batch: Batch) -> Batch:
        for c in self.connectors:
            batch = c(episodes, batch)
        return batch


def columns_from_episodes(episodes: List[Episode], batch: Batch) -> Batch:
    """Flatten episode fragments into columnar arrays."""
    batch[Columns.OBS] = np.concatenate(
        [np.stack(ep.obs) for ep in episodes]).astype(np.float32)
    batch[Columns.ACTIONS] = np.concatenate(
        [np.asarray(ep.actions) for ep in episodes])
    batch[Columns.REWARDS] = np.concatenate(
        [np.asarray(ep.rewards, np.float32) for ep in episodes])
    batch[Columns.ACTION_LOGP] = np.concatenate(
        [np.asarray(ep.logps, np.float32) for ep in episodes])
    batch[Columns.VF_PREDS] = np.concatenate(
        [np.asarray(ep.vf_preds, np.float32) for ep in episodes])
    return batch


class GAE:
    """Generalized advantage estimation over episode fragments.

    Reference: the learner GAE connector + `rllib/evaluation/
    postprocessing.py` compute_advantages. Truncated/open fragments are
    bootstrapped with the module's value of `last_obs`."""

    def __init__(self, gamma: float = 0.99, lambda_: float = 0.95,
                 module=None, params_getter: Callable = None):
        self.gamma = gamma
        self.lambda_ = lambda_
        self.module = module
        self.params_getter = params_getter

    def _bootstrap_value(self, ep: Episode, params) -> float:
        if ep.terminated or self.module is None or params is None:
            return 0.0
        out = self.module.forward_inference(params, ep.last_obs[None, :])
        return float(np.asarray(out[Columns.VF_PREDS])[0])

    def __call__(self, episodes: List[Episode], batch: Batch) -> Batch:
        # fetch weights once per batch — in remote-learner mode the
        # getter is an actor round-trip
        params = (self.params_getter()
                  if self.params_getter is not None else None)
        advs, targets = [], []
        for ep in episodes:
            rewards = np.asarray(ep.rewards, np.float32)
            values = np.asarray(ep.vf_preds, np.float32)
            last_v = self._bootstrap_value(ep, params)
            next_values = np.append(values[1:], last_v)
            deltas = rewards + self.gamma * next_values - values
            adv = np.zeros_like(deltas)
            acc = 0.0
            for t in range(len(deltas) - 1, -1, -1):
                acc = deltas[t] + self.gamma * self.lambda_ * acc
                adv[t] = acc
            advs.append(adv)
            targets.append(adv + values)
        batch[Columns.ADVANTAGES] = np.concatenate(advs)
        batch[Columns.VALUE_TARGETS] = np.concatenate(targets)
        return batch


def standardize_advantages(episodes: List[Episode], batch: Batch) -> Batch:
    adv = batch[Columns.ADVANTAGES]
    batch[Columns.ADVANTAGES] = (adv - adv.mean()) / \
        max(1e-6, adv.std())
    return batch


def sequence_batch(episodes: List[Episode], max_len: int = 0) -> Batch:
    """Pad episode fragments into [B, T] row-major arrays with a
    validity mask — the layout V-trace needs (reference: IMPALA's
    learner queue batches of trajectories). Episodes longer than T are
    SPLIT into chained rows (never truncated): each non-final chunk
    bootstraps from the next chunk's first observation, the final chunk
    carries the episode's own terminated flag and last_obs.
    """
    T = max_len or max(ep.length for ep in episodes)
    rows = []  # (slice of ep, terminated, bootstrap_obs)
    for ep in episodes:
        for start in range(0, ep.length, T):
            end = min(start + T, ep.length)
            final = end == ep.length
            boot = (ep.last_obs if ep.last_obs is not None
                    else ep.obs[-1]) if final else ep.obs[end]
            rows.append((ep, start, end,
                         ep.terminated and final, boot))
    B = len(rows)
    obs_dim = episodes[0].obs[0].shape[-1]
    obs = np.zeros((B, T, obs_dim), np.float32)
    actions = np.zeros((B, T), np.int64)
    rewards = np.zeros((B, T), np.float32)
    logp = np.zeros((B, T), np.float32)
    mask = np.zeros((B, T), np.float32)
    terminated = np.zeros((B,), np.float32)
    last_obs = np.zeros((B, obs_dim), np.float32)
    for b, (ep, start, end, term, boot) in enumerate(rows):
        n = end - start
        obs[b, :n] = np.stack(ep.obs[start:end])
        actions[b, :n] = ep.actions[start:end]
        rewards[b, :n] = ep.rewards[start:end]
        logp[b, :n] = ep.logps[start:end]
        mask[b, :n] = 1.0
        terminated[b] = float(term)
        last_obs[b] = boot
    return {
        Columns.OBS: obs, Columns.ACTIONS: actions,
        Columns.REWARDS: rewards, Columns.ACTION_LOGP: logp,
        "mask": mask, Columns.TERMINATEDS: terminated,
        "last_obs": last_obs,
    }
