"""ConnectorV2 pipelines — episodes → train batch.

Reference: `rllib/connectors/connector_v2.py:18` and the learner-pipeline
GAE connector (`rllib/connectors/learner/
general_advantage_estimation.py`). Kept as plain composable callables:
each connector takes and returns the (episodes, batch) pair.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.env.env_runner import Episode

Batch = Dict[str, np.ndarray]


class ConnectorPipeline:
    def __init__(self, connectors: List[Callable]):
        self.connectors = list(connectors)

    def __call__(self, episodes: List[Episode], batch: Batch) -> Batch:
        for c in self.connectors:
            batch = c(episodes, batch)
        return batch


def columns_from_episodes(episodes: List[Episode], batch: Batch) -> Batch:
    """Flatten episode fragments into columnar arrays."""
    batch[Columns.OBS] = np.concatenate(
        [np.stack(ep.obs) for ep in episodes]).astype(np.float32)
    batch[Columns.ACTIONS] = np.concatenate(
        [np.asarray(ep.actions) for ep in episodes])
    batch[Columns.REWARDS] = np.concatenate(
        [np.asarray(ep.rewards, np.float32) for ep in episodes])
    batch[Columns.ACTION_LOGP] = np.concatenate(
        [np.asarray(ep.logps, np.float32) for ep in episodes])
    batch[Columns.VF_PREDS] = np.concatenate(
        [np.asarray(ep.vf_preds, np.float32) for ep in episodes])
    return batch


class GAE:
    """Generalized advantage estimation over episode fragments.

    Reference: the learner GAE connector + `rllib/evaluation/
    postprocessing.py` compute_advantages. Truncated/open fragments are
    bootstrapped with the module's value of `last_obs`."""

    def __init__(self, gamma: float = 0.99, lambda_: float = 0.95,
                 module=None, params_getter: Callable = None):
        self.gamma = gamma
        self.lambda_ = lambda_
        self.module = module
        self.params_getter = params_getter

    def _bootstrap_value(self, ep: Episode, params) -> float:
        if ep.terminated or self.module is None or params is None:
            return 0.0
        out = self.module.forward_inference(params, ep.last_obs[None, :])
        return float(np.asarray(out[Columns.VF_PREDS])[0])

    def __call__(self, episodes: List[Episode], batch: Batch) -> Batch:
        # fetch weights once per batch — in remote-learner mode the
        # getter is an actor round-trip
        params = (self.params_getter()
                  if self.params_getter is not None else None)
        advs, targets = [], []
        for ep in episodes:
            rewards = np.asarray(ep.rewards, np.float32)
            values = np.asarray(ep.vf_preds, np.float32)
            last_v = self._bootstrap_value(ep, params)
            next_values = np.append(values[1:], last_v)
            deltas = rewards + self.gamma * next_values - values
            adv = np.zeros_like(deltas)
            acc = 0.0
            for t in range(len(deltas) - 1, -1, -1):
                acc = deltas[t] + self.gamma * self.lambda_ * acc
                adv[t] = acc
            advs.append(adv)
            targets.append(adv + values)
        batch[Columns.ADVANTAGES] = np.concatenate(advs)
        batch[Columns.VALUE_TARGETS] = np.concatenate(targets)
        return batch


def standardize_advantages(episodes: List[Episode], batch: Batch) -> Batch:
    adv = batch[Columns.ADVANTAGES]
    batch[Columns.ADVANTAGES] = (adv - adv.mean()) / \
        max(1e-6, adv.std())
    return batch
