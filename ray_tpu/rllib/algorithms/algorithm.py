"""Algorithm: the top-level RL trainer, runnable standalone or under Tune.

Reference: `rllib/algorithms/algorithm.py:213` — Algorithm subclasses
Tune's `Trainable`; `setup` (:579) builds the `EnvRunnerGroup` +
`LearnerGroup`, and each `train()`/`step()` call runs the per-algorithm
`training_step` (:1586) that orchestrates sample → update → weight
broadcast. Same shape here: subclass `ray_tpu.tune.Trainable`, so
`Tuner(PPO, param_space=...)` works exactly like a Train/Tune run.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.env_runner import EnvRunnerGroup
from ray_tpu.tune.trainable import Trainable


class Algorithm(Trainable):
    """Drive sample→update→broadcast; one `step()` = one training
    iteration (reference `training_step`)."""

    #: subclasses bind their Learner and default config
    learner_cls: Type[Learner] = None
    config_cls: Type[AlgorithmConfig] = AlgorithmConfig

    def __init__(self, config: Optional[AlgorithmConfig] = None):
        super().__init__()
        self._algo_config = config
        self.env_runner_group: Optional[EnvRunnerGroup] = None
        self.learner_group: Optional[LearnerGroup] = None
        # pre-set so stop()/cleanup() are safe when setup() fails early
        self._eval_runner = None
        self._output_writer = None
        self._setup_called = False
        if config is not None:
            # standalone construction (config.build_algo()) — Tune-hosted
            # instances defer to setup(param_space_dict)
            self.setup({})

    # -- Trainable interface ----------------------------------------------

    def setup(self, config: Dict[str, Any]) -> None:
        """Shared scaffolding (guard, config merge, output writer,
        iteration counter); the algorithm-specific spec inference and
        runner/learner-group construction live in `_build_groups` so
        variants (e.g. MultiAgentPPO) override that hook instead of
        re-implementing setup."""
        if self._setup_called:
            return
        self._setup_called = True
        cfg = (self._algo_config.copy() if self._algo_config is not None
               else self.default_config())
        if config:
            cfg.update_from_dict(config)
        self.algo_config = cfg
        env_creator = cfg.env_creator()
        self._env_creator = env_creator
        self._build_groups(cfg, env_creator)
        if cfg.output:
            from ray_tpu.rllib.offline.io import JsonWriter
            self._output_writer = JsonWriter(cfg.output)
        self._iteration = 0

    def _build_groups(self, cfg: AlgorithmConfig, env_creator) -> None:
        """Infer the module spec from the env and build the learner +
        env-runner groups. Overridable construction hook."""
        probe = env_creator()
        try:
            obs_dim = int(np.prod(probe.observation_space.shape))
            space = probe.action_space
            if hasattr(space, "n"):  # Discrete
                act_dim, discrete = int(space.n), True
                scale, offset = 1.0, 0.0
            else:  # Box: per-dim affine tanh squashing onto [low, high]
                act_dim = int(np.prod(space.shape))
                discrete = False
                low = np.asarray(space.low, np.float64).ravel()
                high = np.asarray(space.high, np.float64).ravel()
                if not (np.isfinite(low).all()
                        and np.isfinite(high).all()):
                    raise ValueError(
                        f"continuous algorithms need a bounded Box "
                        f"action space; got low={low}, high={high}")
                scale = tuple(((high - low) / 2).tolist())
                offset = tuple(((high + low) / 2).tolist())
        finally:
            probe.close()
        self.spec = RLModuleSpec(
            observation_dim=obs_dim, action_dim=act_dim,
            hidden=cfg.hidden, discrete=discrete, action_scale=scale,
            action_offset=offset, module_class=cfg.module_class)
        self.learner_group = LearnerGroup(
            type(self).learner_cls, self.spec, cfg.learner_config(),
            num_learners=cfg.num_learners,
            num_devices_per_learner=cfg.num_devices_per_learner,
            seed=cfg.seed,
            resources_per_learner=cfg.resources_per_learner)
        self.env_runner_group = EnvRunnerGroup(
            env_creator, self.spec,
            num_env_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_env_runner,
            seed=cfg.seed, explore_config=cfg.explore_config)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())

    @classmethod
    def default_config(cls) -> AlgorithmConfig:
        return cls.config_cls(algo_class=cls)

    def step(self) -> Dict[str, Any]:
        self._iteration += 1
        results = self.training_step()
        metrics = self.env_runner_group.get_metrics()
        returns = [m["episode_return_mean"] for m in metrics
                   if m.get("episode_return_mean") is not None]
        results["episode_return_mean"] = (
            float(np.mean(returns)) if returns else float("nan"))
        results["num_episodes"] = int(
            sum(m.get("num_episodes", 0) for m in metrics))
        results["training_iteration"] = self._iteration
        interval = self.algo_config.evaluation_interval
        if interval and self._iteration % interval == 0:
            results["evaluation"] = self.evaluate()
        return results

    def evaluate(self) -> Dict[str, Any]:
        """Greedy (explore=False) rollouts with the current weights.

        Reference: `Algorithm.evaluate` (`rllib/algorithms/
        algorithm.py:1061`) — like the reference's dedicated evaluation
        workers, this samples on a SEPARATE local runner so greedy eval
        episodes never pollute the training runners' episode metrics or
        interrupt their in-flight episodes.
        """
        from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner

        if self._eval_runner is None:
            self._eval_runner = SingleAgentEnvRunner(
                self._env_creator, self.spec,
                num_envs=self.algo_config.num_envs_per_env_runner,
                seed=self.algo_config.seed + 999_983)
        self._eval_runner.set_weights(self.learner_group.get_weights())
        episodes = self._eval_runner.sample(
            self.algo_config.evaluation_duration, explore=False)
        returns = [ep.total_reward for ep in episodes if ep.terminated
                   or ep.truncated]
        return {
            "episode_return_mean": (float(np.mean(returns)) if returns
                                    else float("nan")),
            "num_episodes": len(returns),
        }

    def record_episodes(self, episodes) -> None:
        """Persist sampled episodes when `config.offline_data(output=)`
        is set (reference: env-runner output writers)."""
        if self._output_writer is not None:
            self._output_writer.write(episodes)

    def train(self) -> Dict[str, Any]:
        """Standalone stepping (outside Tune): one training iteration."""
        return self.step()

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # -- checkpointing -----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self._iteration,
            "algo_state": self.get_algo_state(),
        }
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"),
                  "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._iteration = state["iteration"]
        self.set_algo_state(state.get("algo_state", {}))
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())

    def get_algo_state(self) -> Dict[str, Any]:
        """Algorithm-specific extra state (e.g. DQN epsilon schedule)."""
        return {}

    def set_algo_state(self, state: Dict[str, Any]) -> None:
        pass

    def cleanup(self) -> None:
        self.stop()

    def stop(self) -> None:
        if self.env_runner_group is not None:
            self.env_runner_group.stop()
        if self.learner_group is not None:
            self.learner_group.stop()
        if self._eval_runner is not None:
            self._eval_runner._envs.close()
            self._eval_runner = None
