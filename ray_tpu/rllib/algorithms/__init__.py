from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig

__all__ = ["Algorithm", "AlgorithmConfig", "APPO", "APPOConfig",
           "PPO", "PPOConfig", "DQN",
           "DQNConfig", "IMPALA", "IMPALAConfig", "BC", "BCConfig",
           "MARWIL", "MARWILConfig", "SAC", "SACConfig"]
