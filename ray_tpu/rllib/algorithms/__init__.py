from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.multi_agent_ppo import (
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig

__all__ = ["Algorithm", "AlgorithmConfig", "APPO", "APPOConfig",
           "CQL", "CQLConfig", "PPO", "PPOConfig", "DQN",
           "DQNConfig", "IMPALA", "IMPALAConfig",
           "MultiAgentPPO", "MultiAgentPPOConfig", "BC", "BCConfig",
           "MARWIL", "MARWILConfig", "SAC", "SACConfig"]
