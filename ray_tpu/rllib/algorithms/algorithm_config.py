"""AlgorithmConfig: the fluent builder that parameterizes an Algorithm.

Reference: `rllib/algorithms/algorithm_config.py` (4.9k LoC) — rebuilt as
a compact dataclass-backed fluent API covering the new-stack surface the
rebuilt Algorithm actually consumes: environment / env_runners / training
/ learners / rl_module / evaluation groups, `to_dict`/`from_dict` so Tune
param_space dicts overlay cleanly, and `build_algo()`.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Tuple, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: Any = None  # gym id string or callable creator
        self.env_config: Dict[str, Any] = {}
        # env runners (reference .env_runners())
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.explore_config: Dict[str, Any] = {}
        # training (shared knobs; algos add their own via .training())
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.grad_clip: float = 0.5
        self.train_batch_size: int = 2000
        self.minibatch_size: int = 256
        self.num_epochs: int = 8
        # learners (reference .learners())
        self.num_learners: int = 0
        self.num_devices_per_learner: int = 1
        self.resources_per_learner: Optional[Dict[str, float]] = None
        # rl module
        self.hidden: Tuple[int, ...] = (64, 64)
        self.module_class: Optional[type] = None
        # evaluation (reference .evaluation())
        self.evaluation_interval: int = 0  # iterations; 0 = off
        self.evaluation_duration: int = 500  # env steps per evaluate()
        # offline data (reference .offline_data())
        self.input_: Any = None  # path/glob of recorded episode shards
        self.output: Any = None  # directory to record sampled episodes
        # multi-agent (reference .multi_agent(); empty = single-agent)
        self.policies: Dict[str, Any] = {}
        self.policy_mapping_fn: Optional[Callable] = None
        # misc
        self.seed: int = 0
        self.extra: Dict[str, Any] = {}

    # -- fluent groups (each returns self, reference style) ----------------

    def environment(self, env: Any = None, *,
                    env_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    explore_config: Optional[Dict] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore_config is not None:
            self.explore_config = dict(explore_config)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        """Set any training hyperparameter; unknown keys land in `extra`
        and flow into the Learner config (so algo-specific knobs like
        `clip_param` need no dedicated field)."""
        for k, v in kwargs.items():
            if hasattr(self, k) and k != "extra":
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 num_devices_per_learner: Optional[int] = None,
                 resources_per_learner: Optional[Dict] = None
                 ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_devices_per_learner is not None:
            self.num_devices_per_learner = num_devices_per_learner
        if resources_per_learner is not None:
            self.resources_per_learner = dict(resources_per_learner)
        return self

    def rl_module(self, *, hidden: Optional[Tuple[int, ...]] = None,
                  module_class: Optional[type] = None
                  ) -> "AlgorithmConfig":
        if hidden is not None:
            self.hidden = tuple(hidden)
        if module_class is not None:
            self.module_class = module_class
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_duration: Optional[int] = None
                   ) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def offline_data(self, *, input_: Any = None, output: Any = None
                     ) -> "AlgorithmConfig":
        if input_ is not None:
            self.input_ = input_
        if output is not None:
            self.output = output
        return self

    def debugging(self, *, seed: Optional[int] = None
                  ) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # -- dict interop (Tune param_space overlay) ---------------------------

    _SKIP = {"algo_class"}

    def to_dict(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items()
             if k not in self._SKIP and k != "extra"}
        d.update(self.extra)
        return d

    def multi_agent(self, *, policies: Optional[Dict[str, Any]] = None,
                    policy_mapping_fn: Optional[Callable] = None
                    ) -> "AlgorithmConfig":
        """Declare the policy modules and the agent->module routing.

        `policies` maps module ids to an RLModuleSpec or None (None =
        infer the spec from the env's per-agent spaces); every agent id
        is routed by `policy_mapping_fn(agent_id) -> module_id`
        (reference `algorithm_config.py` .multi_agent()).
        """
        if policies is not None:
            self.policies = dict(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if k in self._SKIP:
                continue
            if hasattr(self, k) and k != "extra":
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    # -- derived -----------------------------------------------------------

    def learner_config(self) -> Dict[str, Any]:
        cfg = {"lr": self.lr, "gamma": self.gamma,
               "grad_clip": self.grad_clip}
        cfg.update(self.extra)
        return cfg

    def env_creator(self) -> Callable:
        env = self.env
        env_config = self.env_config
        if callable(env):
            if env_config:
                return lambda: env(env_config)
            return env
        if isinstance(env, str):
            def make():
                import gymnasium as gym
                return gym.make(env, **env_config)
            return make
        raise ValueError(f"config.environment(env=...) required; got "
                         f"{env!r}")

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(config=self)
