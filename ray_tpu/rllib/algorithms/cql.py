"""CQL — conservative Q-learning for offline continuous control.

Reference: `rllib/algorithms/cql/cql.py` (+ `cql/torch/
cql_torch_learner.py`): SAC's twin-critic/auto-alpha machinery trained
purely from a logged dataset, with the CQL(H) conservative regularizer
pushing Q down on out-of-distribution actions (logsumexp over sampled
actions) and up on dataset actions. TPU-first shape: the regularizer's
sampled-action Q evaluations are batched into the same jitted update as
the SAC loss — `num_sampled_actions` uniform + policy + next-policy
samples evaluated in one [3n, B] critic pass, no Python loop.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import (SACConfig, SACLearner,
                                          SACModule, _squash)
from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.offline.io import JsonReader
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class CQLLearner(SACLearner):
    """SAC loss + the CQL(H) conservative term on both critics."""

    def compute_loss(self, params, batch, aux=None):
        m: SACModule = self.module
        sac_loss, stats = super().compute_loss(params, batch, aux)
        n = int(self.config.get("num_sampled_actions", 10))
        cql_alpha = self.config.get("cql_alpha", 5.0)

        key = jax.random.wrap_key_data(
            jnp.asarray(batch["cql_rng"], jnp.uint32))
        k_unif, k_pol, k_next = jax.random.split(key, 3)
        obs = batch[Columns.OBS]
        B = obs.shape[0]

        # --- candidate actions: [n, B, A] each ---------------------------
        lo, hi = m.offset - m.scale, m.offset + m.scale
        a_unif = jax.random.uniform(
            k_unif, (n, B, m.spec.action_dim),
            minval=jnp.broadcast_to(lo, (m.spec.action_dim,)),
            maxval=jnp.broadcast_to(hi, (m.spec.action_dim,)))
        # uniform log-density over the box (importance correction)
        log_unif = -jnp.sum(jnp.log(2.0 * jnp.broadcast_to(
            m.scale, (m.spec.action_dim,)) + 1e-8))

        # The conservative term trains the CRITICS only (reference CQL
        # attaches it to the critic optimizers and detaches the policy
        # log-probs) — sample from a gradient-stopped copy of the policy
        # so cql_alpha * logsumexp can't push a spurious actor gradient.
        frozen_policy = jax.lax.stop_gradient(params["policy"])

        def policy_samples(o, k):
            mean, log_std = m.policy.apply(frozen_policy, o)
            ks = jax.random.split(k, n)
            a, logp = jax.vmap(
                lambda kk: _squash(mean, log_std, kk, m.scale, m.offset)
            )(ks)
            return a, logp  # [n, B, A], [n, B]

        a_pol, logp_pol = policy_samples(obs, k_pol)
        a_nxt, logp_nxt = policy_samples(batch[Columns.NEXT_OBS], k_next)

        cand = jnp.concatenate([a_unif, a_pol, a_nxt], axis=0)  # [3n,B,A]
        log_dens = jnp.concatenate([
            jnp.full((n, B), log_unif), logp_pol, logp_nxt], axis=0)

        def ood_term(q_params):
            q = jax.vmap(lambda a: m.q.apply(q_params, obs, a))(cand)
            # CQL(H): logsumexp with importance weights, minus data Q
            lse = jax.scipy.special.logsumexp(
                q - log_dens, axis=0) - jnp.log(3.0 * n)
            q_data = m.q.apply(q_params, obs, batch[Columns.ACTIONS])
            # mean critic value on the policy's own (OOD) actions — the
            # quantity the conservative penalty is meant to suppress
            q_ood = jnp.mean(q[n:2 * n])
            return jnp.mean(lse - q_data), q_ood

        t1, ood1 = ood_term(params["q1"])
        t2, ood2 = ood_term(params["q2"])
        cql_term = t1 + t2
        loss = sac_loss + cql_alpha * cql_term
        stats = dict(stats)
        stats["cql_loss"] = cql_term
        stats["q_ood_mean"] = 0.5 * (ood1 + ood2)
        return loss, stats


class CQLConfig(SACConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or CQL)
        self.num_epochs = 1
        self.extra.update({
            "cql_alpha": 5.0,
            "num_sampled_actions": 10,
            "num_updates_per_iteration": 64,
        })


class CQL(Algorithm):
    """Offline: `config.offline_data(input_=...)` + an env for space
    inference and greedy evaluation (reference CQL evaluates the learned
    policy on the real env too)."""

    learner_cls = CQLLearner
    config_cls = CQLConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        cfg = self.algo_config
        if self.spec.discrete:
            raise ValueError("CQL targets continuous (Box) action "
                             "spaces (reference CQL extends SAC)")
        if not cfg.input_:
            raise ValueError(
                "offline algorithms need config.offline_data(input_=...)")
        # load the whole logged dataset into a flat transition buffer
        reader = JsonReader(cfg.input_, seed=cfg.seed)
        self.replay = ReplayBuffer(capacity=10_000_000, seed=cfg.seed)
        for ep in reader.iter_episodes():
            if ep.length:
                self.replay.add_episode(ep)
        if not len(self.replay):
            raise ValueError(f"no transitions found in {cfg.input_!r}")

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        x = cfg.extra
        stats: Dict[str, float] = {}
        num_updates = 0
        for u in range(x["num_updates_per_iteration"]):
            batch = self.replay.sample(cfg.train_batch_size)
            batch["rng"] = np.asarray(
                [cfg.seed & 0xFFFFFFFF,
                 (977 * self._iteration + u) & 0xFFFFFFFF], np.uint32)
            batch["cql_rng"] = np.asarray(
                [(cfg.seed + 1) & 0xFFFFFFFF,
                 (991 * self._iteration + u) & 0xFFFFFFFF], np.uint32)
            s = self.learner_group.update_from_batch(batch)
            for k, v in s.items():
                stats[k] = stats.get(k, 0.0) + v
            num_updates += 1
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())
        out = {k: v / max(1, num_updates) for k, v in stats.items()}
        out["num_offline_steps_trained"] = int(
            num_updates * cfg.train_batch_size)
        return out
