"""Multi-agent PPO: independent per-module PPO over a shared rollout.

Reference: `rllib/algorithms/ppo/ppo.py:421` training_step combined with
the multi-agent plumbing of `rllib/env/multi_agent_env_runner.py` and
`rllib/core/rl_module/multi_rl_module.py`. Shared policies are many
agents mapped onto one module by `policy_mapping_fn`; each module gets
its own LearnerGroup (single jitted update program per module — see the
design note in ray_tpu/rllib/env/multi_agent.py).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.ppo import PPOConfig
from ray_tpu.rllib.connectors import (GAE, columns_from_episodes,
                                      standardize_advantages)
from ray_tpu.rllib.core.learner import PPOLearner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.multi_agent import MultiAgentEnvRunnerGroup


def _spec_from_spaces(obs_space, act_space, cfg) -> RLModuleSpec:
    obs_dim = int(np.prod(obs_space.shape))
    if hasattr(act_space, "n"):
        return RLModuleSpec(observation_dim=obs_dim,
                            action_dim=int(act_space.n),
                            hidden=cfg.hidden, discrete=True,
                            module_class=cfg.module_class)
    low = np.asarray(act_space.low, np.float64).ravel()
    high = np.asarray(act_space.high, np.float64).ravel()
    return RLModuleSpec(
        observation_dim=obs_dim, action_dim=int(np.prod(act_space.shape)),
        hidden=cfg.hidden, discrete=False,
        action_scale=tuple(((high - low) / 2).tolist()),
        action_offset=tuple(((high + low) / 2).tolist()),
        module_class=cfg.module_class)


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or MultiAgentPPO)


class MultiAgentPPO(Algorithm):
    """`config.multi_agent(policies=..., policy_mapping_fn=...)` +
    `config.environment(env=<MultiAgentEnv creator>)`."""

    config_cls = MultiAgentPPOConfig

    def _build_groups(self, cfg, env_creator) -> None:
        """Multi-module construction: one LearnerGroup per policy module
        plus the multi-agent runner fleet (the shared setup scaffolding —
        config merge, output writer, iteration counter — stays in
        Algorithm.setup)."""
        if not cfg.policies or cfg.policy_mapping_fn is None:
            raise ValueError(
                "MultiAgentPPO needs config.multi_agent(policies=..., "
                "policy_mapping_fn=...)")
        mapping = cfg.policy_mapping_fn

        # infer unspecified module specs from the env's declared spaces
        probe = env_creator()
        try:
            self.specs: Dict[str, RLModuleSpec] = {}
            for mid, spec in cfg.policies.items():
                if spec is None:
                    agents = [a for a in probe.possible_agents
                              if mapping(a) == mid]
                    if not agents:
                        raise ValueError(
                            f"no agent maps to module {mid!r}")
                    a = agents[0]
                    spec = _spec_from_spaces(
                        probe.observation_spaces[a],
                        probe.action_spaces[a], cfg)
                self.specs[mid] = spec
        finally:
            probe.close()

        self.learner_groups: Dict[str, LearnerGroup] = {
            mid: LearnerGroup(
                PPOLearner, spec, cfg.learner_config(),
                num_learners=cfg.num_learners,
                num_devices_per_learner=cfg.num_devices_per_learner,
                seed=cfg.seed + i,
                resources_per_learner=cfg.resources_per_learner)
            for i, (mid, spec) in enumerate(self.specs.items())
        }
        self.env_runner_group = MultiAgentEnvRunnerGroup(
            env_creator, self.specs, mapping,
            num_env_runners=cfg.num_env_runners, seed=cfg.seed,
            explore_config=cfg.explore_config)
        self.env_runner_group.sync_weights(self._weights())
        self._gae = {
            mid: GAE(gamma=cfg.gamma,
                     lambda_=cfg.extra.get("lambda_", 0.95),
                     module=spec.build(),
                     params_getter=self.learner_groups[mid].get_weights)
            for mid, spec in self.specs.items()
        }

    def _weights(self) -> Dict[str, Any]:
        return {mid: lg.get_weights()
                for mid, lg in self.learner_groups.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        by_module = self.env_runner_group.sample(cfg.train_batch_size)
        self.record_episodes(
            [ep for eps in by_module.values() for ep in eps])
        rng = np.random.default_rng(cfg.seed + self._iteration)
        out: Dict[str, Any] = {}
        total_steps = 0
        for mid, episodes in by_module.items():
            if not episodes:
                continue
            batch = columns_from_episodes(episodes, {})
            batch = self._gae[mid](episodes, batch)
            batch = standardize_advantages(episodes, batch)
            n = batch["actions"].shape[0]
            total_steps += n
            stats: Dict[str, float] = {}
            num_mb = 0
            lg = self.learner_groups[mid]
            for _ in range(cfg.num_epochs):
                perm = rng.permutation(n)
                for start in range(0, n, cfg.minibatch_size):
                    idx = perm[start:start + cfg.minibatch_size]
                    if idx.shape[0] < 2:
                        continue
                    mb = {k: v[idx] for k, v in batch.items()}
                    s = lg.update_from_batch(mb)
                    for k, v in s.items():
                        stats[k] = stats.get(k, 0.0) + v
                    num_mb += 1
            for k, v in stats.items():
                out[f"{mid}/{k}"] = v / max(1, num_mb)
        self.env_runner_group.sync_weights(self._weights())
        out["num_env_steps_sampled"] = int(total_steps)
        return out

    def evaluate(self) -> Dict[str, Any]:
        from ray_tpu.rllib.env.multi_agent import MultiAgentEnvRunner

        if self._eval_runner is None:
            self._eval_runner = MultiAgentEnvRunner(
                self._env_creator, self.specs,
                self.algo_config.policy_mapping_fn,
                seed=self.algo_config.seed + 999_983)
        self._eval_runner.set_weights(self._weights())
        self._eval_runner.sample(
            self.algo_config.evaluation_duration, explore=False)
        return self._eval_runner.get_metrics()

    # -- checkpointing (per-module learner states) -------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = {
            "learners": {mid: lg.get_state()
                         for mid, lg in self.learner_groups.items()},
            "iteration": self._iteration,
        }
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"),
                  "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        for mid, st in state["learners"].items():
            self.learner_groups[mid].set_state(st)
        self._iteration = state["iteration"]
        self.env_runner_group.sync_weights(self._weights())

    def stop(self) -> None:
        if getattr(self, "env_runner_group", None) is not None:
            self.env_runner_group.stop()
        for lg in getattr(self, "learner_groups", {}).values():
            lg.stop()
        if self._eval_runner is not None:
            self._eval_runner._env.close()
            self._eval_runner = None
