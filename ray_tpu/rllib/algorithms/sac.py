"""SAC — soft actor-critic for continuous (Box) action spaces.

Reference: `rllib/algorithms/sac/sac.py` (+ torch policy losses in
`sac/torch/sac_torch_learner.py`): off-policy maximum-entropy RL with a
tanh-squashed Gaussian policy, twin Q networks with polyak-averaged
targets, and auto-tuned entropy temperature alpha. TPU-first delta:
policy/Q/alpha live in ONE param pytree updated by one jitted step —
cross-component gradient isolation is done with `stop_gradient` on the
relevant subtrees instead of separate optimizers, so the whole update
is a single compiled program.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import Columns, RLModule, RLModuleSpec
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class _GaussianPolicyNet(nn.Module):
    hidden: tuple
    action_dim: int

    @nn.compact
    def __call__(self, obs):
        x = obs
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        mean = nn.Dense(self.action_dim)(x)
        log_std = jnp.clip(nn.Dense(self.action_dim)(x),
                           LOG_STD_MIN, LOG_STD_MAX)
        return mean, log_std


class _QSANet(nn.Module):
    """Q(s, a) critic over concatenated observation+action."""

    hidden: tuple

    @nn.compact
    def __call__(self, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        for h in self.hidden:
            x = nn.tanh(nn.Dense(h)(x))
        return jnp.squeeze(nn.Dense(1)(x), -1)


def _squash(mean, log_std, key, scale, offset=0.0):
    """Reparameterized affine-tanh-Gaussian sample + log-prob (with the
    tanh change-of-variables correction; the offset shifts the support
    without affecting the density)."""
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape)
    a = jnp.tanh(u) * scale + offset
    logp_u = -0.5 * (((u - mean) / std) ** 2
                     + 2 * log_std + jnp.log(2 * jnp.pi))
    # d (tanh(u)*s + o) / du = s * (1 - tanh(u)^2)
    correction = jnp.log(scale * (1 - jnp.tanh(u) ** 2) + 1e-6)
    logp = (logp_u - correction).sum(axis=-1)
    return a, logp


class SACModule(RLModule):
    """Policy + twin critics + log-alpha in one param tree."""

    def __init__(self, spec: RLModuleSpec):
        super().__init__(spec)
        self.policy = _GaussianPolicyNet(spec.hidden, spec.action_dim)
        self.q = _QSANet(spec.hidden)
        self.scale = jnp.asarray(spec.action_scale, jnp.float32)
        self.offset = jnp.asarray(spec.action_offset, jnp.float32)

    def init_params(self, rng: jax.Array):
        k1, k2, k3 = jax.random.split(rng, 3)
        obs = jnp.zeros((1, self.spec.observation_dim), jnp.float32)
        act = jnp.zeros((1, self.spec.action_dim), jnp.float32)
        return {
            "policy": self.policy.init(k1, obs),
            "q1": self.q.init(k2, obs, act),
            "q2": self.q.init(k3, obs, act),
            "log_alpha": jnp.zeros(()),
        }

    def forward_inference(self, params, obs):
        mean, _ = self.policy.apply(params["policy"], obs)
        return {"actions": jnp.tanh(mean) * self.scale + self.offset}

    def forward_exploration(self, params, obs, rng):
        mean, log_std = self.policy.apply(params["policy"], obs)
        a, logp = _squash(mean, log_std, rng, self.scale, self.offset)
        return {"actions": a, Columns.ACTION_LOGP: logp}

    def forward_train(self, params, batch):
        mean, log_std = self.policy.apply(params["policy"],
                                          batch[Columns.OBS])
        return {"mean": mean, "log_std": log_std}

    def q_values(self, params, obs, actions):
        return (self.q.apply(params["q1"], obs, actions),
                self.q.apply(params["q2"], obs, actions))


class SACLearner(Learner):
    """Combined jitted update: critic TD loss on batch actions, actor
    loss on reparameterized fresh actions against stop-gradient
    critics, and the alpha (temperature) loss. Targets polyak-update in
    `_after_update` (reference uses tau-averaged target nets)."""

    def __init__(self, spec: RLModuleSpec, config=None, seed: int = 0,
                 num_devices: int = 1):
        super().__init__(spec, config, seed, num_devices)
        self.target_q = {"q1": self.params["q1"],
                         "q2": self.params["q2"]}
        self.tau = self.config.get("tau", 0.005)
        self.target_entropy = self.config.get(
            "target_entropy", -float(spec.action_dim))

    def _aux_state(self):
        return self.target_q

    def compute_loss(self, params, batch, aux=None):
        m: SACModule = self.module
        target_q = aux if aux is not None else self.target_q
        gamma = self.config.get("gamma", 0.99)
        # reparameterization key arrives as raw uint32 key data in the
        # batch (a jit input — fresh noise per update without retracing)
        key = jax.random.wrap_key_data(
            jnp.asarray(batch["rng"], jnp.uint32))
        k_next, k_new = jax.random.split(key)

        obs = batch[Columns.OBS]
        actions = batch[Columns.ACTIONS]
        alpha = jnp.exp(params["log_alpha"])

        # --- critic loss (batch actions, frozen targets) ----------------
        mean_n, log_std_n = m.policy.apply(params["policy"],
                                           batch[Columns.NEXT_OBS])
        a_next, logp_next = _squash(mean_n, log_std_n, k_next, m.scale,
                                    m.offset)
        tq1 = m.q.apply(target_q["q1"], batch[Columns.NEXT_OBS], a_next)
        tq2 = m.q.apply(target_q["q2"], batch[Columns.NEXT_OBS], a_next)
        not_done = 1.0 - batch[Columns.TERMINATEDS].astype(jnp.float32)
        backup = jax.lax.stop_gradient(
            batch[Columns.REWARDS] + gamma * not_done *
            (jnp.minimum(tq1, tq2) - alpha * logp_next))
        q1 = m.q.apply(params["q1"], obs, actions)
        q2 = m.q.apply(params["q2"], obs, actions)
        q_loss = jnp.mean((q1 - backup) ** 2) + \
            jnp.mean((q2 - backup) ** 2)

        # --- actor loss (fresh actions, frozen critics) -----------------
        mean, log_std = m.policy.apply(params["policy"], obs)
        a_new, logp_new = _squash(mean, log_std, k_new, m.scale,
                                  m.offset)
        q1_sg = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                       params["q1"])
        q2_sg = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                       params["q2"])
        q_new = jnp.minimum(m.q.apply(q1_sg, obs, a_new),
                            m.q.apply(q2_sg, obs, a_new))
        policy_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * logp_new - q_new)

        # --- temperature loss -------------------------------------------
        alpha_loss = -jnp.mean(
            params["log_alpha"]
            * jax.lax.stop_gradient(logp_new + self.target_entropy))

        loss = q_loss + policy_loss + alpha_loss
        return loss, {
            "q_loss": q_loss, "policy_loss": policy_loss,
            "alpha_loss": alpha_loss, "alpha": alpha,
            "q_mean": jnp.mean(q1), "entropy": -jnp.mean(logp_new),
        }

    def _after_update(self) -> None:
        tau = self.tau
        self.target_q = jax.tree_util.tree_map(
            lambda t, o: (1.0 - tau) * t + tau * o, self.target_q,
            {"q1": self.params["q1"], "q2": self.params["q2"]})

    def get_state(self):
        from ray_tpu.rllib.core.rl_module import params_to_numpy

        state = super().get_state()
        state["target_q"] = params_to_numpy(self.target_q)
        return state

    def set_state(self, state) -> None:
        super().set_state(state)
        if "target_q" in state:
            self.target_q = jax.tree_util.tree_map(
                jnp.asarray, state["target_q"])


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or SAC)
        self.module_class = SACModule
        self.lr = 3e-4
        self.train_batch_size = 256
        self.rollout_fragment_length = 200
        self.grad_clip = 10.0
        self.extra.update({
            "tau": 0.005,
            "learning_starts": 1000,
            "num_updates_per_iteration": 32,
            "replay_capacity": 100_000,
        })


class SAC(Algorithm):
    learner_cls = SACLearner
    config_cls = SACConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        if self.spec.discrete:
            raise ValueError(
                "SAC targets continuous (Box) action spaces; use DQN "
                "for discrete envs (reference SAC has the same core)")
        x = self.algo_config.extra
        self.replay = ReplayBuffer(capacity=x["replay_capacity"],
                                   seed=self.algo_config.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        x = cfg.extra
        episodes = self.env_runner_group.sample(
            cfg.rollout_fragment_length)
        self.record_episodes(episodes)
        for ep in episodes:
            if ep.length:
                self.replay.add_episode(ep)
        stats: Dict[str, float] = {}
        num_updates = 0
        if len(self.replay) >= x["learning_starts"]:
            for u in range(x["num_updates_per_iteration"]):
                batch = self.replay.sample(cfg.train_batch_size)
                # fresh reparameterization noise per update, threaded
                # through the jitted loss as raw key data (no retrace,
                # no dependence on jax's key representation)
                batch["rng"] = np.asarray(
                    [cfg.seed & 0xFFFFFFFF,
                     (977 * self._iteration + u) & 0xFFFFFFFF],
                    np.uint32)
                s = self.learner_group.update_from_batch(batch)
                for k, v in s.items():
                    stats[k] = stats.get(k, 0.0) + v
                num_updates += 1
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        out = {k: v / max(1, num_updates) for k, v in stats.items()}
        out["replay_size"] = len(self.replay)
        out["num_updates"] = num_updates
        return out
