"""APPO — asynchronous PPO: IMPALA's actor-learner loop with PPO's
clipped surrogate on V-trace-corrected advantages.

Reference: `rllib/algorithms/appo/appo.py` (+ the torch learner's
clipped loss over vtrace advantages). Reuses this repo's IMPALA
machinery end to end — same sequence batches, same `vtrace_returns`,
same stale-weight broadcasting — and swaps only the policy surrogate
(the `_policy_loss` hook on IMPALALearner), which tolerates more
policy lag per sampled batch (hence more SGD passes than IMPALA's
default).
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.core.learner import IMPALALearner


class APPOLearner(IMPALALearner):
    """V-trace targets + PPO clip on the importance ratio."""

    def _policy_loss(self, target_logp, behavior_logp, pg_adv, mask, n):
        ratio = jnp.exp(target_logp - behavior_logp)
        clip_eps = self.config.get("clip_param", 0.2)
        surrogate = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * pg_adv)
        return (-(surrogate * mask).sum() / n,
                {"mean_ratio": (ratio * mask).sum() / n})


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or APPO)
        self.extra.update({
            "clip_param": 0.2,
            # the clip objective tolerates more reuse of a sampled
            # batch than IMPALA's plain pg term
            "num_updates_per_batch": 4,
        })


class APPO(IMPALA):
    learner_cls = APPOLearner
    config_cls = APPOConfig
