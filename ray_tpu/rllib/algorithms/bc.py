"""BC and MARWIL — offline RL algorithms over recorded episodes.

Reference: `rllib/algorithms/bc/bc.py` (behavior cloning = MARWIL with
beta=0) and `rllib/algorithms/marwil/marwil.py` — train from an offline
dataset (`config.offline_data(input_=...)`) instead of env runners;
MARWIL weights the log-likelihood by exponentiated advantages
(exp(beta * (G - V))) and regresses V toward the Monte-Carlo return.
The env in the config is used only for `evaluate()` rollouts.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.offline.io import JsonReader


def offline_batch(episodes, gamma: float) -> Dict[str, np.ndarray]:
    """Columnar batch with discounted returns-to-go as VALUE_TARGETS.

    Truncated episodes bootstrap nothing (reference MARWIL also uses raw
    Monte-Carlo returns from the logged data)."""
    obs, actions, returns = [], [], []
    for ep in episodes:
        if not ep.length:
            continue
        r = np.asarray(ep.rewards, np.float32)
        g = np.zeros_like(r)
        acc = 0.0
        for t in range(len(r) - 1, -1, -1):
            acc = r[t] + gamma * acc
            g[t] = acc
        obs.append(np.stack(ep.obs))
        actions.append(np.asarray(ep.actions))
        returns.append(g)
    return {
        Columns.OBS: np.concatenate(obs).astype(np.float32),
        Columns.ACTIONS: np.concatenate(actions),
        Columns.VALUE_TARGETS: np.concatenate(returns),
    }


class MARWILLearner(Learner):
    """Advantage-weighted log-likelihood + value regression.

    beta=0 degenerates to plain behavior cloning (the reference makes BC
    exactly this: `bc.py` subclasses MARWIL with beta forced to 0)."""

    def compute_loss(self, params, batch, aux=None):
        out = self.module.forward_train(params, batch)
        logits = out[Columns.ACTION_DIST_INPUTS]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[Columns.ACTIONS].astype(jnp.int32)
        logp = logp_all[jnp.arange(logits.shape[0]), actions]
        beta = self.config.get("beta", 0.0)
        if beta:
            values = out[Columns.VF_PREDS]
            targets = batch[Columns.VALUE_TARGETS]
            adv = jax.lax.stop_gradient(targets - values)
            # clip the exponent for numerical safety (reference clips
            # advantages via a moving norm estimate)
            w = jnp.exp(jnp.clip(beta * adv, -10.0, 10.0))
            policy_loss = -jnp.mean(w * logp)
            vf_loss = jnp.mean((values - targets) ** 2)
        else:
            policy_loss = -jnp.mean(logp)
            vf_loss = jnp.asarray(0.0)
        probs = jax.nn.softmax(logits)
        entropy = -jnp.mean(jnp.sum(probs * logp_all, axis=-1))
        loss = policy_loss \
            + self.config.get("vf_loss_coeff", 1.0) * vf_loss \
            - self.config.get("entropy_coeff", 0.0) * entropy
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": entropy}


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or MARWIL)
        self.lr = 1e-3
        self.train_batch_size = 2000
        self.minibatch_size = 256
        self.num_epochs = 1
        self.extra.update({
            "beta": 1.0,
            "vf_loss_coeff": 1.0,
            "entropy_coeff": 0.0,
        })


class BCConfig(MARWILConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or BC)
        self.extra["beta"] = 0.0


class MARWIL(Algorithm):
    learner_cls = MARWILLearner
    config_cls = MARWILConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        cfg = self.algo_config
        if not cfg.input_:
            raise ValueError(
                "offline algorithms need config.offline_data(input_=...)")
        self.reader = JsonReader(cfg.input_, seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        episodes = self.reader.sample_episodes(cfg.train_batch_size)
        batch = offline_batch(episodes, cfg.gamma)
        n = batch[Columns.ACTIONS].shape[0]
        rng = np.random.default_rng(cfg.seed + self._iteration)
        stats: Dict[str, float] = {}
        num_mb = 0
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = perm[start:start + cfg.minibatch_size]
                if idx.shape[0] < 2:
                    continue
                mb = {k: v[idx] for k, v in batch.items()}
                s = self.learner_group.update_from_batch(mb)
                for k, v in s.items():
                    stats[k] = stats.get(k, 0.0) + v
                num_mb += 1
        out = {k: v / max(1, num_mb) for k, v in stats.items()}
        out["num_offline_steps_trained"] = int(n)
        return out


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta pinned to 0 (the reference's
    `bc.py` validates exactly this relationship)."""

    learner_cls = MARWILLearner
    config_cls = BCConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        if self.algo_config.extra.get("beta", 0.0) != 0.0:
            raise ValueError("BC requires beta=0; use MARWIL for beta>0")
