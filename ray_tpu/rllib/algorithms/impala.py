"""IMPALA — importance-weighted actor-learner architecture.

Reference: `rllib/algorithms/impala/impala.py` — decoupled acting and
learning: env runners sample with weights that lag the learner, and the
V-trace corrections (`vtrace_tf.py`, rebuilt as `vtrace_returns` in jax)
make the off-policy updates sound. Here the lag is explicit:
weights broadcast to the runners every `broadcast_interval` iterations,
so between broadcasts the learner trains on behavior-stale trajectories
exactly as the asynchronous reference does.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.connectors import sequence_batch
from ray_tpu.rllib.core.learner import IMPALALearner


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.train_batch_size = 500  # env steps per iteration
        self.rollout_fragment_length = 50
        self.extra.update({
            "vtrace_rho_clip": 1.0,
            "vtrace_c_clip": 1.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "broadcast_interval": 2,  # iterations between weight syncs
            # SGD passes per sampled batch (reference: replay-capable
            # learner queue; v-trace re-corrects against the updated
            # policy on every pass)
            "num_updates_per_batch": 2,
        })


class IMPALA(Algorithm):
    learner_cls = IMPALALearner
    config_cls = IMPALAConfig

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        episodes = [
            ep for ep in self.env_runner_group.sample(
                cfg.train_batch_size)
            if ep.length
        ]
        self.record_episodes(episodes)
        batch = sequence_batch(episodes,
                               max_len=cfg.rollout_fragment_length)
        for _ in range(cfg.extra["num_updates_per_batch"]):
            stats = self.learner_group.update_from_batch(batch)
        # decoupled acting: runners keep sampling with stale weights
        # between broadcasts (v-trace corrects the lag)
        if self._iteration % cfg.extra["broadcast_interval"] == 0:
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        stats["num_env_steps_sampled"] = int(
            sum(ep.length for ep in episodes))
        return stats
