"""PPO — proximal policy optimization on the new-stack components.

Reference: `rllib/algorithms/ppo/ppo.py:395` (class) / :421
(`training_step`): synchronous on-policy loop — sample a train batch from
the env runners, GAE-postprocess, run minibatch SGD epochs on the
learner group, broadcast fresh weights back to the runners.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.connectors import (
    GAE,
    columns_from_episodes,
    standardize_advantages,
)
from ray_tpu.rllib.core.learner import PPOLearner


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or PPO)
        self.lr = 3e-4
        self.train_batch_size = 2000
        self.minibatch_size = 256
        self.num_epochs = 8
        # PPO loss knobs (flow into the Learner via extra)
        self.extra.update({
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.0,
            "lambda_": 0.95,
        })


class PPO(Algorithm):
    learner_cls = PPOLearner
    config_cls = PPOConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        cfg = self.algo_config
        # GAE bootstraps open episode fragments with the current value fn
        module = self.spec.build()
        self._gae = GAE(
            gamma=cfg.gamma,
            lambda_=cfg.extra.get("lambda_", 0.95),
            module=module,
            params_getter=self.learner_group.get_weights)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        self.record_episodes(episodes)
        batch = columns_from_episodes(episodes, {})
        batch = self._gae(episodes, batch)
        batch = standardize_advantages(episodes, batch)
        n = batch["actions"].shape[0]
        rng = np.random.default_rng(cfg.seed + self._iteration)
        stats: Dict[str, float] = {}
        num_minibatches = 0
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n, cfg.minibatch_size):
                idx = perm[start:start + cfg.minibatch_size]
                if idx.shape[0] < 2:
                    continue
                mb = {k: v[idx] for k, v in batch.items()}
                s = self.learner_group.update_from_batch(mb)
                for k, v in s.items():
                    stats[k] = stats.get(k, 0.0) + v
                num_minibatches += 1
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())
        out = {k: v / max(1, num_minibatches) for k, v in stats.items()}
        out["num_env_steps_sampled"] = int(n)
        return out
