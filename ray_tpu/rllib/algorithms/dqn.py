"""DQN — double-DQN with (prioritized) replay on the new stack.

Reference: `rllib/algorithms/dqn/dqn.py` `training_step`: sample with
epsilon-greedy exploration into a replay buffer; once `learning_starts`
transitions are stored, run `num_updates_per_iteration` sampled-batch
updates (priorities refreshed from TD errors), syncing weights to the
env runners each iteration.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import DQNLearner
from ray_tpu.rllib.core.rl_module import QModule
from ray_tpu.rllib.utils.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class: type = None):
        super().__init__(algo_class or DQN)
        self.module_class = QModule
        self.lr = 1e-3
        self.train_batch_size = 64
        self.rollout_fragment_length = 100
        self.extra.update({
            "target_update_freq": 200,
            "learning_starts": 500,
            "num_updates_per_iteration": 16,
            "replay_capacity": 50_000,
            "prioritized_replay": False,
            "epsilon_initial": 1.0,
            "epsilon_final": 0.05,
            "epsilon_decay_iterations": 30,
        })


class DQN(Algorithm):
    learner_cls = DQNLearner
    config_cls = DQNConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        x = self.algo_config.extra
        if x.get("prioritized_replay"):
            self.replay = PrioritizedReplayBuffer(
                capacity=x["replay_capacity"],
                seed=self.algo_config.seed)
        else:
            self.replay = ReplayBuffer(capacity=x["replay_capacity"],
                                       seed=self.algo_config.seed)

    def _epsilon(self) -> float:
        x = self.algo_config.extra
        frac = min(1.0, self._iteration /
                   max(1, x["epsilon_decay_iterations"]))
        return x["epsilon_initial"] + frac * (x["epsilon_final"] -
                                              x["epsilon_initial"])

    def training_step(self) -> Dict[str, Any]:
        cfg = self.algo_config
        x = cfg.extra
        eps = self._epsilon()
        # runner-side exploration: epsilon flows through forward_exploration
        self.env_runner_group.set_explore_config({"epsilon": eps})
        episodes = self.env_runner_group.sample(
            cfg.rollout_fragment_length)
        self.record_episodes(episodes)
        for ep in episodes:
            if ep.length:
                self.replay.add_episode(ep)
        stats: Dict[str, float] = {}
        num_updates = 0
        if len(self.replay) >= x["learning_starts"]:
            for _ in range(x["num_updates_per_iteration"]):
                batch = self.replay.sample(cfg.train_batch_size)
                idx = batch.pop("_indices")
                s = self.learner_group.update_from_batch(batch)
                if x.get("prioritized_replay"):
                    batch["_indices"] = idx
                    td = self.learner_group.td_errors(
                        {k: v for k, v in batch.items()
                         if k != "_indices"})
                    self.replay.update_priorities(idx, td)
                for k, v in s.items():
                    stats[k] = stats.get(k, 0.0) + v
                num_updates += 1
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        out = {k: v / max(1, num_updates) for k, v in stats.items()}
        out["epsilon"] = eps
        out["replay_size"] = len(self.replay)
        return out
