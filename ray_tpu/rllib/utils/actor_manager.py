"""FaultTolerantActorManager.

Reference: `rllib/utils/actor_manager.py:196` — fan-out RPCs to a fleet,
mark unhealthy actors, and restore them; used by EnvRunnerGroup (and
LearnerGroup) so a dead sampler never sinks the training loop.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, List, Optional

import ray_tpu


class FaultTolerantActorManager:
    def __init__(self, actors: List[Any],
                 restart_fn: Optional[Callable[..., Any]] = None,
                 max_restarts: int = 3):
        # restart_fn may take zero args or the dead actor's index —
        # index-aware factories let callers rebuild per-actor state
        # (e.g. the runner's unique RNG seed) instead of a shared default.
        self._actors = list(actors)
        self._healthy = [True] * len(actors)
        self._restart_fn = restart_fn
        self._restart_takes_index = bool(
            restart_fn is not None
            and inspect.signature(restart_fn).parameters)
        self._restarts = [0] * len(actors)
        self.max_restarts = max_restarts
        self._restarted_idxs: set = set()

    def num_healthy(self) -> int:
        return sum(self._healthy)

    @property
    def actors(self) -> List[Any]:
        return [a for a, h in zip(self._actors, self._healthy) if h]

    def _gather(self, refs: List[Any], idxs: List[int],
                timeout: float) -> List[Any]:
        """Collect results, marking failed actors unhealthy (and
        restarting them when possible). Shared failure path for every
        fan-out variant."""
        results = []
        for i, ref in zip(idxs, refs):
            try:
                results.append(ray_tpu.get(ref, timeout=timeout))
            except Exception:
                self._mark_unhealthy(i)
        return results

    def foreach(self, fn: Callable[[Any], Any],
                timeout: float = 300.0) -> List[Any]:
        """fn(actor) -> ObjectRef for each healthy actor; returns results
        from the actors that succeeded."""
        return self.foreach_zip(lambda a, _item: fn(a),
                                [None] * len(self._actors),
                                timeout=timeout)

    def foreach_zip(self, fn: Callable[[Any, Any], Any], items: List[Any],
                    timeout: float = 300.0) -> List[Any]:
        """fn(actor, item) -> ObjectRef, pairing healthy actors with items
        positionally; failures are marked unhealthy and dropped."""
        refs, idxs = [], []
        healthy = [(i, a) for i, (a, h)
                   in enumerate(zip(self._actors, self._healthy)) if h]
        for (i, a), item in zip(healthy, items):
            refs.append(fn(a, item))
            idxs.append(i)
        return self._gather(refs, idxs, timeout)

    def foreach_one(self, fn: Callable[[Any], Any],
                    timeout: float = 300.0,
                    exclude: Optional[set] = None) -> List[Any]:
        """fn on the first healthy actor only (skipping ``exclude``
        indices while an alternative exists); returns a one-element list
        (empty if every actor is dead)."""
        order = [i for i, h in enumerate(self._healthy) if h]
        if exclude:
            preferred = [i for i in order if i not in exclude]
            order = preferred + [i for i in order if i in exclude]
        for i in order:
            if not self._healthy[i]:
                continue
            got = self._gather([fn(self._actors[i])], [i], timeout)
            if got:
                return got
        return []

    def _mark_unhealthy(self, i: int) -> None:
        self._healthy[i] = False
        if self._restart_fn is not None and \
                self._restarts[i] < self.max_restarts:
            try:
                ray_tpu.kill(self._actors[i])
            except Exception:
                pass
            if self._restart_takes_index:
                self._actors[i] = self._restart_fn(i)
            else:
                self._actors[i] = self._restart_fn()
            self._restarts[i] += 1
            self._healthy[i] = True
            self._restarted_idxs.add(i)

    def take_restarted(self) -> set:
        """Indices of actors restarted since the last call — callers that
        replicate state across the fleet (LearnerGroup) must re-sync the
        fresh replicas (from a NON-restarted survivor) when non-empty."""
        fired = self._restarted_idxs
        self._restarted_idxs = set()
        return fired

    def probe_health(self, timeout: float = 10.0) -> int:
        """Ping every actor (even marked-unhealthy ones after restart)."""
        for i, a in enumerate(self._actors):
            try:
                ray_tpu.get(a.ping.remote(), timeout=timeout)
                self._healthy[i] = True
            except Exception:
                self._mark_unhealthy(i)
        return self.num_healthy()

    def stop(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._healthy = [False] * len(self._actors)
