"""FaultTolerantActorManager.

Reference: `rllib/utils/actor_manager.py:196` — fan-out RPCs to a fleet,
mark unhealthy actors, and restore them; used by EnvRunnerGroup (and
LearnerGroup) so a dead sampler never sinks the training loop.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import ray_tpu


class FaultTolerantActorManager:
    def __init__(self, actors: List[Any],
                 restart_fn: Optional[Callable[[], Any]] = None,
                 max_restarts: int = 3):
        self._actors = list(actors)
        self._healthy = [True] * len(actors)
        self._restart_fn = restart_fn
        self._restarts = [0] * len(actors)
        self.max_restarts = max_restarts

    def num_healthy(self) -> int:
        return sum(self._healthy)

    @property
    def actors(self) -> List[Any]:
        return [a for a, h in zip(self._actors, self._healthy) if h]

    def foreach(self, fn: Callable[[Any], Any],
                timeout: float = 300.0) -> List[Any]:
        """fn(actor) -> ObjectRef for each healthy actor; gather results,
        marking failures unhealthy (and restarting them if possible).
        Returns results from the actors that succeeded."""
        refs = []
        idxs = []
        for i, (a, h) in enumerate(zip(self._actors, self._healthy)):
            if not h:
                continue
            refs.append(fn(a))
            idxs.append(i)
        results = []
        for i, ref in zip(idxs, refs):
            try:
                results.append(ray_tpu.get(ref, timeout=timeout))
            except Exception:
                self._mark_unhealthy(i)
        return results

    def _mark_unhealthy(self, i: int) -> None:
        self._healthy[i] = False
        if self._restart_fn is not None and \
                self._restarts[i] < self.max_restarts:
            try:
                ray_tpu.kill(self._actors[i])
            except Exception:
                pass
            self._actors[i] = self._restart_fn()
            self._restarts[i] += 1
            self._healthy[i] = True

    def probe_health(self, timeout: float = 10.0) -> int:
        """Ping every actor (even marked-unhealthy ones after restart)."""
        for i, a in enumerate(self._actors):
            try:
                ray_tpu.get(a.ping.remote(), timeout=timeout)
                self._healthy[i] = True
            except Exception:
                self._mark_unhealthy(i)
        return self.num_healthy()

    def stop(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._healthy = [False] * len(self._actors)
