"""Replay buffers.

Reference: `rllib/utils/replay_buffers/` — `EpisodeReplayBuffer`
(`episode_replay_buffer.py:14`) and the prioritized variant
(`prioritized_episode_replay_buffer.py`). Stored as flat transition
arrays (columnar, numpy) — the TPU-friendly layout for batch sampling.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.rllib.core.rl_module import Columns
from ray_tpu.rllib.env.env_runner import Episode


class ReplayBuffer:
    """Uniform FIFO transition buffer."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: Dict[str, List] = {
            Columns.OBS: [], Columns.ACTIONS: [], Columns.REWARDS: [],
            Columns.NEXT_OBS: [], Columns.TERMINATEDS: [],
        }
        self.rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self._cols[Columns.ACTIONS])

    def add_episode(self, ep: Episode) -> None:
        obs = ep.obs + ([ep.last_obs] if ep.last_obs is not None
                        else [ep.obs[-1]])
        for t in range(ep.length):
            self._add_row(obs[t], ep.actions[t], ep.rewards[t],
                          obs[t + 1],
                          ep.terminated and t == ep.length - 1)

    def _add_row(self, o, a, r, o2, term) -> None:
        self._cols[Columns.OBS].append(np.asarray(o, np.float32))
        self._cols[Columns.ACTIONS].append(a)
        self._cols[Columns.REWARDS].append(np.float32(r))
        self._cols[Columns.NEXT_OBS].append(np.asarray(o2, np.float32))
        self._cols[Columns.TERMINATEDS].append(bool(term))
        if len(self) > self.capacity:
            for col in self._cols.values():
                col.pop(0)
        self._on_add()

    def _on_add(self) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, len(self), size=batch_size)
        return self._gather(idx)

    def _gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            Columns.OBS: np.stack(
                [self._cols[Columns.OBS][i] for i in idx]),
            Columns.ACTIONS: np.asarray(
                [self._cols[Columns.ACTIONS][i] for i in idx]),
            Columns.REWARDS: np.asarray(
                [self._cols[Columns.REWARDS][i] for i in idx],
                np.float32),
            Columns.NEXT_OBS: np.stack(
                [self._cols[Columns.NEXT_OBS][i] for i in idx]),
            Columns.TERMINATEDS: np.asarray(
                [self._cols[Columns.TERMINATEDS][i] for i in idx],
                np.float32),
            "_indices": idx,
        }


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference
    `prioritized_episode_replay_buffer.py`): P(i) ∝ p_i^α with
    importance-sampling weights w_i = (N·P(i))^-β."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities: List[float] = []
        self._max_priority = 1.0

    def _on_add(self) -> None:
        self._priorities.append(self._max_priority)
        while len(self._priorities) > len(self):
            self._priorities.pop(0)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        pri = np.asarray(self._priorities) ** self.alpha
        probs = pri / pri.sum()
        idx = self.rng.choice(len(self), size=batch_size, p=probs)
        batch = self._gather(idx)
        weights = (len(self) * probs[idx]) ** (-self.beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        return batch

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        for i, td in zip(indices, td_errors):
            p = float(abs(td)) + 1e-6
            self._priorities[int(i)] = p
            self._max_priority = max(self._max_priority, p)
