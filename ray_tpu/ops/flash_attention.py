"""Flash attention as a Pallas TPU kernel.

Why: XLA's dense softmax attention materialises the [B, H, T, T] score
tensor in HBM (f32: ~800 MB per layer at B=16, T=1024) and walks it
several times (mask, max, exp, sum, divide, then again in the backward).
At GPT-2 shapes that makes attention bandwidth-bound at ~15% of peak.
This kernel streams Q blocks and K/V chunks through VMEM with an online
softmax — scores never exist in HBM, in either direction.

Design notes (see /opt/skills/guides/pallas_guide.md):
- forward grid = (batch, heads, num_q_blocks, num_kv_chunks); the last
  grid dim is innermost-sequential on TPU, so the online-softmax state
  (running max / sum / output accumulator) lives in VMEM scratch across
  a Q block's KV chunks and flushes once.
- **Causal chunk skipping** (round-3 change; the round-2 kernel executed
  fully-masked blocks on the claim that skipping cost more than it
  saved — false at long context, where the masked upper triangle is
  ~half the FLOPs): a KV chunk entirely above the diagonal skips ALL its
  compute via `pl.when` — only its (overlapped, ~free) DMA remains. At
  T=4096 this removes ~45% of attention FLOPs; the same predicate trims
  the backward. Work per Q block now scales with its causal KV range,
  not T.
- Chunked KV also removes the old whole-K/V-in-VMEM residency, so the
  T <= 4096 kernel cap is gone: VMEM per step is O(block_q*d + block_k*d),
  independent of T.
- Softmax statistics are f32 on the VPU; all matmuls (Q@K^T, P@V, and
  the grad contractions) run on the MXU with preferred_element_type=f32.
- The backward recomputes P per chunk from the forward's per-row
  logsumexp (a [B, H, T, 1] side output — the trailing singleton exists
  because a [1,1,block_q] block fails the TPU (8,128) tiling rule on its
  last two dims) — two kernels, one accumulating dQ over KV chunks, one
  accumulating dK/dV over Q blocks (and over the query-head group for
  GQA, by folding heads-in-group into the innermost grid dim). The
  softmax-jacobian rowsum delta = rowsum(dO*O) is precomputed once as an
  XLA prologue, so O never streams through the kernels.

Reference parity: fcas/ray has no TPU attention kernel; its model-side
equivalent is torch F.scaled_dot_product_attention (flash backend) used
by its model code. API matches `full_attention` in
ray_tpu/parallel/ring_attention.py so models can swap it in untouched.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128  # stats scratch is [block_q, _LANES]; only column 0 is real


def _pick_block_q(t: int) -> int:
    for cand in (512, 256, 128):
        if t % cand == 0:
            return cand
    return 0  # caller falls back to the XLA path


def _pick_block_k(t: int) -> int:
    """Measured policy (GPT-2 125M on v5e, tok/s, same session):
    at T=1024 whole-KV wins (117.7k vs 108.2k for bk=512 — chunking
    overhead beats the 25% causal skip at short context); at T=4096 the
    r5 sweep measured bq=512: bk=1024 74.1k > bk=2048 72.7k > bk=512
    63.9k — finer chunks skip more of the upper triangle (executed
    cols 20480 vs 24576 of 18432 useful) until per-chunk overhead wins.
    So: whole-KV up to 2048, chunks of 1024 beyond.
    """
    if t <= 2048:
        return t
    for cand in (1024, 512, 256, 128):
        if t % cand == 0:
            return cand
    return 0


# f32 [block_q, block_k] temporaries (s, p, ds, dp live together in the
# backward) put a hard product cap on the block pair: 1024x2048 was
# measured to overflow the 16 MB VMEM scoped allocation
_MAX_BLOCK_PRODUCT = 512 * 2048


def _chunk_scores(q, k, scale, causal, qi, ki, block_q, block_k):
    """[bq, bk] f32 masked scores of one Q block vs one KV chunk."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def _run_causal(run_pred, body):
    """Run `body(masked=True)` under the chunk-skip predicate. A
    diagonal/below-diagonal mask split was tried in r5 (mask-free body
    for chunks strictly below the diagonal): consistently SLOWER
    end-to-end (73.5k vs 74.7k tok/s at T=4096, A/B in one session) —
    the duplicated pl.when bodies cost more than the iota+where mask
    they avoid, so every running chunk takes the masked path."""

    @pl.when(run_pred)
    def _():
        body(masked=True)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    def body(masked):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        s = _chunk_scores(q, k, scale, masked, qi, ki, block_q, block_k)
        m_prev = m_s[:, :1]                                   # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, 1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)                        # [bq, 1]
        p = jnp.exp(s - m_new)                                # [bq, bk]
        l_new = l_s[:, :1] * corr + jnp.sum(p, 1, keepdims=True)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, d]
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    if causal:
        # causal chunk skip: a KV chunk starting past this Q block's
        # last row is fully masked — no compute (this is where the
        # long-context FLOPs go from O(T^2) to O(T^2/2))
        run = ki * block_k <= qi * block_q + block_q - 1
        _run_causal(run, body)
    else:
        body(masked=False)

    @pl.when(ki == nk - 1)
    def _():
        l = l_s[:, :1]
        o_ref[0, 0, :, :] = (acc_s[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = m_s[:, :1] + jnp.log(l)


# --------------------------------------------------------------------------
# single-chunk specializations (block_k == T)
#
# When the whole K/V fits one chunk (the <= 2048-token hot path — GPT-2
# T=1024 trains here), the online-softmax machinery is pure overhead:
# per-step stat broadcasts into [bq, 128] lanes, the correction
# exp/multiply, and scratch init/flush cost ~9% end-to-end (measured
# r2->r3: 129.0k -> 117.2k tok/s/chip). These kernels do the plain
# one-pass softmax over [bq, T] scores instead — no scratch, no
# correction — while still emitting the logsumexp the shared chunked
# backward structure expects.
# --------------------------------------------------------------------------

def _fwd_single_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                       scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    s = _chunk_scores(q, k, scale, causal, qi, 0, block_q, block_k)
    m = jnp.max(s, axis=1, keepdims=True)                     # [bq, 1]
    p = jnp.exp(s - m)                                        # [bq, T]
    l = jnp.sum(p, axis=1, keepdims=True)                     # [bq, 1]
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bq, d]
    o_ref[0, 0, :, :] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0, :, :] = m + jnp.log(l)


def _bwd_single_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                       scale, causal, block_q, block_k, group):
    # grid = (b, h, nq): ONE fused pass produces dq (written per step)
    # and dk/dv (accumulated in [T, d] scratch across a KV head's whole
    # query-head group x Q blocks, flushed once per KV head) — the
    # scores/probabilities are computed ONCE and q/k/v/do stream through
    # VMEM once, where split dq/dkv kernels would pay both twice.
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when((qi == 0) & (hi % group == 0))
    def _():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :]
    lse = lse_ref[0, 0, :, :]                                 # [bq, 1]
    delta = delta_ref[0, 0, :, :]                             # [bq, 1]
    s = _chunk_scores(q, k, scale, causal, qi, 0, block_q, block_k)
    p = jnp.exp(s - lse)                                      # [bq, T]
    dp = jax.lax.dot_general(
        do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bq, T]
    ds = p * (dp - delta)                                     # [bq, T]
    dq_ref[0, 0, :, :] = (jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale).astype(dq_ref.dtype)
    dk_s[...] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # [T, d]
    dv_s[...] += jax.lax.dot_general(
        p.astype(do_ref.dtype), do.astype(do_ref.dtype),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [T, d]

    @pl.when((qi == nq - 1) & (hi % group == group - 1))
    def _():
        dk_ref[0, 0, :, :] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_s[...].astype(dv_ref.dtype)


def _fwd(q, k, v, scale, causal, block_q, block_k, group, interpret):
    b, h, t, d = q.shape
    if block_k == t:
        grid = (b, h, t // block_q)
        q_spec = pl.BlockSpec((1, 1, block_q, d),
                              lambda bi, hi, qi: (bi, hi, qi, 0))
        kv_spec = pl.BlockSpec((1, 1, t, d),
                               lambda bi, hi, qi: (bi, hi // group, 0, 0))
        lse_spec = pl.BlockSpec((1, 1, block_q, 1),
                                lambda bi, hi, qi: (bi, hi, qi, 0))
        return pl.pallas_call(
            functools.partial(_fwd_single_kernel, scale=scale,
                              causal=causal, block_q=block_q, block_k=t),
            grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[q_spec, lse_spec],
            out_shape=[jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                       jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
            interpret=interpret,
        )(q, k, v)
    grid = (b, h, t // block_q, t // block_k)
    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    # GQA: query head hi reads KV head hi // group (group == 1 -> MHA)
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda bi, hi, qi, ki: (bi, hi // group, ki, 0))
    # trailing singleton: a [1,1,bq] block fails the TPU (8,128) tiling
    # rule on its last two dims; [1,1,bq,1] block over [b,h,t,1] passes
    # (last dim full, second-to-last divisible by 8)
    lse_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[q_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32),
                        pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_s, *, scale, causal, block_q, block_k):
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        dq_s[...] = jnp.zeros_like(dq_s)

    def body(masked):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]                             # [bq, 1]
        delta = delta_ref[0, 0, :, :]                         # [bq, 1]
        s = _chunk_scores(q, k, scale, masked, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta)                                 # [bq, bk]
        dq_s[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, d]

    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
        _run_causal(run, body)
    else:
        body(masked=False)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0, 0, :, :] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_s, dv_s, *,
                scale, causal, block_q, block_k, group, nq):
    # grid = (b, h_kv, nk, group * nq): the innermost dim folds the KV
    # head's whole query-head group x Q blocks, so dK/dV accumulate in
    # VMEM scratch across all of them and flush once per (kv head, ki).
    ki = pl.program_id(2)
    jj = pl.program_id(3)
    qi = jj % nq
    nj = pl.num_programs(3)

    @pl.when(jj == 0)
    def _():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    def body(masked):
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, :, :]
        delta = delta_ref[0, 0, :, :]
        s = _chunk_scores(q, k, scale, masked, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_s[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bk, d]
        dv_s[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do.astype(do_ref.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, d]

    if causal:
        # causal skip (roles swapped): a Q block entirely above this KV
        # chunk contributes nothing to its dK/dV
        run = qi * block_q + block_q - 1 >= ki * block_k
        _run_causal(run, body)
    else:
        body(masked=False)

    @pl.when(jj == nj - 1)
    def _():
        dk_ref[0, 0, :, :] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_s[...].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, group, interpret, res, g):
    q, k, v, out, lse = res
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    nq, nk = t // block_q, t // block_k
    # softmax-jacobian rowsum, computed ONCE (XLA fuses this into one
    # elementwise+reduce pass); O then never enters the kernels
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [b,h,t,1]

    if block_k == t:
        # single-chunk backward: one fused dq/dk/dv kernel
        q_spec = pl.BlockSpec((1, 1, block_q, d),
                              lambda bi, hi, qi: (bi, hi, qi, 0))
        kv_spec = pl.BlockSpec((1, 1, t, d),
                               lambda bi, hi, qi: (bi, hi // group, 0, 0))
        lse_spec = pl.BlockSpec((1, 1, block_q, 1),
                                lambda bi, hi, qi: (bi, hi, qi, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_single_kernel, scale=scale,
                              causal=causal, block_q=block_q, block_k=t,
                              group=group),
            grid=(b, h, nq),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec,
                      lse_spec],
            out_specs=[q_spec, kv_spec, kv_spec],
            out_shape=[
                jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
                jax.ShapeDtypeStruct((b, h_kv, t, d), k.dtype),
                jax.ShapeDtypeStruct((b, h_kv, t, d), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((t, d), jnp.float32),
                            pltpu.VMEM((t, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, lse, delta)
        return dq, dk, dv

    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, d),
                           lambda bi, hi, qi, ki: (bi, hi // group, ki, 0))
    lse_spec = pl.BlockSpec((1, 1, block_q, 1),
                            lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, lse_spec, lse_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dK/dV: per-(kv head, KV chunk) accumulation over group x Q blocks
    gq_spec = pl.BlockSpec(
        (1, 1, block_q, d),
        lambda bi, hk, ki, jj: (bi, hk * group + jj // nq, jj % nq, 0))
    glse_spec = pl.BlockSpec(
        (1, 1, block_q, 1),
        lambda bi, hk, ki, jj: (bi, hk * group + jj // nq, jj % nq, 0))
    gkv_in_spec = pl.BlockSpec((1, 1, block_k, d),
                               lambda bi, hk, ki, jj: (bi, hk, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, group=group,
                          nq=nq),
        grid=(b, h_kv, nk, group * nq),
        in_specs=[gq_spec, gkv_in_spec, gkv_in_spec, gq_spec, glse_spec,
                  glse_spec],
        out_specs=[gkv_in_spec, gkv_in_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h_kv, t, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h_kv, t, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, group, interpret):
    out, _lse = _fwd(q, k, v, scale, causal, block_q, block_k, group,
                     interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, group, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, group,
                    interpret)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Drop-in for `full_attention`: q is [B, T, H, head_dim]; k/v may
    carry fewer (grouped-query) heads — [B, T, H_kv, head_dim] with
    H % H_kv == 0 — which the kernel serves natively via its KV index
    map, with no query-side KV expansion in HBM.

    Falls back to the XLA dense path when (a) not running on TPU (the
    interpret-mode kernel is for tests, not speed) or (b) the shape
    doesn't block evenly — same semantics either way. The chunked-KV
    online softmax has no sequence-length cap (VMEM per step is
    independent of T). For sequence-sharded meshes use ring/Ulysses
    attention (ray_tpu/parallel/ring_attention.py); this kernel is the
    single-chip hot path.
    """
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    bq = block_q or _pick_block_q(t)
    bk = block_k or _pick_block_k(t)
    while bq > 128 and bq * bk > _MAX_BLOCK_PRODUCT:
        bq //= 2  # keep the f32 score temporaries inside scoped VMEM
    if (bq == 0 or bk == 0 or t % bq or t % bk or d % 64 or h % h_kv
            or bq * bk > _MAX_BLOCK_PRODUCT
            or jax.default_backend() != "tpu"):
        from ray_tpu.parallel.ring_attention import full_attention
        return full_attention(q, k, v, causal=causal, scale=scale)
    # kernel layout is [B, H, T, d] so the T dim is block-sliceable
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash(qt, kt, vt, scale, causal, bq, bk, h // h_kv, False)
    return out.transpose(0, 2, 1, 3)
