"""Flash attention as a Pallas TPU kernel.

Why: XLA's dense softmax attention materialises the [B, H, T, T] score
tensor in HBM (f32: ~800 MB per layer at B=16, T=1024) and walks it
several times (mask, max, exp, sum, divide, then again in the backward).
At GPT-2 shapes that makes attention bandwidth-bound at ~15% of peak.
This kernel streams Q blocks through VMEM, computes scores against the
whole K/V (which fit comfortably in VMEM for T <= ~4k at head_dim 64-128)
and writes only the [block_q, head_dim] output back — scores never exist
in HBM, in either the forward or the backward pass.

Design notes (see /opt/skills/guides/pallas_guide.md):
- grid = (batch, heads, num_q_blocks); the last grid dim is innermost-
  sequential on TPU, which the backward exploits to accumulate dK/dV in
  VMEM scratch across Q blocks and flush once at the end.
- Softmax statistics are computed in f32 on the VPU; the matmuls
  (Q@K^T, P@V and the grad contractions) run on the MXU with
  preferred_element_type=f32.
- The backward is a custom VJP whose only residuals are the inputs and
  the output: the softmax normalisers are *recomputed* from the in-VMEM
  score block (one extra max+sum on the VPU) rather than stored — that
  keeps every intermediate tensor out of HBM and sidesteps awkward
  [B, H, T]-shaped outputs that don't tile.
- Causal masking is done in-register with a broadcasted iota; for fully
  masked (upper-triangular) Q/KV block pairs the FLOPs still execute —
  at these sizes skipping them saves less than the pipeline bubbles cost.

Reference parity: fcas/ray has no TPU attention kernel; its model-side
equivalent is torch F.scaled_dot_product_attention (flash backend) used
by its model code. API matches `full_attention` in
ray_tpu/parallel/ring_attention.py so models can swap it in untouched.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _pick_block_q(t: int) -> int:
    # budget the f32 [block_q, T] VMEM temporaries (the backward keeps
    # several live at once: s, p, dp, ds — plus K/V and dK/dV scratch),
    # so the block shrinks as T grows instead of cliffing at ~16 MB VMEM
    if t <= 1024:
        cap = 512
    elif t <= 2048:
        cap = 256
    else:
        cap = 128
    for cand in (512, 256, 128):
        if cand <= cap and t % cand == 0:
            return cand
    return 0  # caller falls back to the XLA path


def _scores(q, k, scale, causal, qi, block_q):
    """[bq, T] f32 masked scores for one Q block — shared by fwd and bwd."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q):
    # refs: q, o [1, 1, bq, d]; k, v [1, 1, T, d]
    qi = pl.program_id(2)
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]

    s = _scores(q, k, scale, causal, qi, block_q)             # [bq, T]
    m = jnp.max(s, axis=1, keepdims=True)                     # [bq, 1]
    p = jnp.exp(s - m)                                        # [bq, T] f32
    l = jnp.sum(p, axis=1, keepdims=True)                     # [bq, 1]
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bq, d]
    o_ref[0, 0, :, :] = (o / l).astype(o_ref.dtype)


def _fwd(q, k, v, scale, causal, block_q, group, interpret):
    b, h, t, d = q.shape
    grid = (b, h, t // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi: (bi, hi, qi, 0))
    # GQA: query head hi reads KV head hi // group (group == 1 -> MHA)
    kv_spec = pl.BlockSpec((1, 1, t, d),
                           lambda bi, hi, qi: (bi, hi // group, 0, 0))
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        interpret=interpret,
    )(q, k, v)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref,
                dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale, causal, block_q, group):
    # grid = (b, h, nq); h then nq iterate sequentially on a TPU core:
    # accumulate dK/dV in f32 VMEM scratch across a KV head's whole
    # group of query heads (GQA) x Q blocks, flush once per KV head.
    hi = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when((qi == 0) & (hi % group == 0))
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    o = o_ref[0, 0, :, :].astype(jnp.float32)

    # recompute the softmax for this block (scores live only in VMEM)
    s = _scores(q, k, scale, causal, qi, block_q)             # [bq, T]
    m = jnp.max(s, axis=1, keepdims=True)
    e = jnp.exp(s - m)
    p = e / jnp.sum(e, axis=1, keepdims=True)                 # [bq, T] f32

    # delta_i = rowsum(dO_i * O_i)  (the -P^T dP P term folded via O)
    delta = jnp.sum(do * o, axis=1, keepdims=True)            # [bq, 1]
    dp = jax.lax.dot_general(
        do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bq, T]
    ds = p * (dp - delta)                                     # [bq, T] f32

    dq = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # [bq, d]
    dq_ref[0, 0, :, :] = dq.astype(dq_ref.dtype)

    dk_acc[...] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale           # [T, d]
    dv_acc[...] += jax.lax.dot_general(
        p.astype(do_ref.dtype), do.astype(do_ref.dtype),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [T, d]

    @pl.when((qi == nq - 1) & (hi % group == group - 1))
    def _():
        dk_ref[0, 0, :, :] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, group, interpret, res, g):
    q, k, v, out = res
    b, h, t, d = q.shape
    h_kv = k.shape[1]
    grid = (b, h, t // block_q)
    q_spec = pl.BlockSpec((1, 1, block_q, d),
                          lambda bi, hi, qi: (bi, hi, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, t, d),
                           lambda bi, hi, qi: (bi, hi // group, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, group=group),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec],
        out_specs=[q_spec, kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h_kv, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h_kv, t, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((t, d), jnp.float32),
                        pltpu.VMEM((t, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, out, g)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, group, interpret):
    return _fwd(q, k, v, scale, causal, block_q, group, interpret)


def _flash_fwd(q, k, v, scale, causal, block_q, group, interpret):
    out = _fwd(q, k, v, scale, causal, block_q, group, interpret)
    return out, (q, k, v, out)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None):
    """Drop-in for `full_attention`: q is [B, T, H, head_dim]; k/v may
    carry fewer (grouped-query) heads — [B, T, H_kv, head_dim] with
    H % H_kv == 0 — which the kernel serves natively via its KV index
    map, with no query-side KV expansion in HBM.

    Falls back to the XLA dense path when (a) not running on TPU (the
    interpret-mode kernel is for tests, not speed), (b) the shape doesn't
    block evenly, or (c) K/V + a score block would overflow VMEM
    (T > 4096) — same semantics either way. For sequence-sharded meshes
    use ring/Ulysses attention (ray_tpu/parallel/ring_attention.py);
    this kernel is the single-chip hot path.
    """
    b, t, h, d = q.shape
    h_kv = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    bq = block_q or _pick_block_q(t)
    if (bq == 0 or t % bq or t > 4096 or d % 64 or h % h_kv
            or jax.default_backend() != "tpu"):
        from ray_tpu.parallel.ring_attention import full_attention
        return full_attention(q, k, v, causal=causal, scale=scale)
    # kernel layout is [B, H, T, d] so the T dim is block-sliceable
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash(qt, kt, vt, scale, causal, bq, h // h_kv, False)
    return out.transpose(0, 2, 1, 3)
