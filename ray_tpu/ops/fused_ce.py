"""Fused LM-head cross-entropy with a hand-written backward.

Why: autodiff of `cross_entropy_loss(model.apply(...), targets)` casts the
[B, T, V] logits to f32 and materialises full-size f32 intermediates
(log_softmax forward, softmax-minus-onehot backward) in HBM — at GPT-2
shapes that's ~6.6 GB written and re-read per pass, and the head goes
~3x slower than its matmul FLOPs justify. This op:

- keeps logits in bf16 end to end; the softmax statistics (row max,
  logsumexp) are f32 *reductions* that XLA fuses into the read loop, so
  no f32 [B, T, V] tensor ever exists in HBM;
- saves the bf16 logits as the residual and rebuilds the f32-free
  gradient `dlogits = exp(s - lse) * coef - onehot(y) * coef` in bf16 in
  the backward (one elementwise pass + a scatter-add at the target
  indices), feeding the two grad matmuls directly.

The chunked scan variant (`chunked_cross_entropy` in models/gpt.py) is
the *memory*-optimal path for huge batch x seq; this is the *speed*-
optimal path while the bf16 logits fit (it trades one [B, T, V] bf16
residual for ~1.5x head speedup).

Reference parity: the reference trains its LM examples through
torch.nn.functional.cross_entropy over fp16/bf16 logits with fused
kernels; this is the TPU-first equivalent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_cross_entropy(hidden, wte, targets, ignore_index: int = -1):
    """Mean token NLL of `hidden @ wte^T` against `targets`.

    hidden: [B, T, D] (bf16 or f32); wte: [V, D]; targets: [B, T] int,
    entries equal to `ignore_index` are excluded from the mean (same
    contract as `cross_entropy_loss`).
    """
    loss, _ = _fused_ce_fwd(hidden, wte, targets, ignore_index)
    return loss


def _fused_ce_fwd(hidden, wte, targets, ignore_index):
    dtype = hidden.dtype
    logits = jnp.einsum("btd,vd->btv", hidden, wte.astype(dtype))
    mask = (targets != ignore_index)
    y = jnp.maximum(targets, 0)
    s32 = logits.astype(jnp.float32)
    m = jnp.max(s32, axis=-1)
    # fused reduction: exp(s - m) feeds the sum without materialising
    lse = m + jnp.log(jnp.sum(jnp.exp(s32 - m[..., None]), axis=-1))
    tgt = jnp.take_along_axis(s32, y[..., None], axis=-1)[..., 0]
    count = jnp.maximum(mask.sum(), 1).astype(jnp.float32)
    loss = jnp.where(mask, lse - tgt, 0.0).sum() / count
    return loss, (hidden, wte, logits, lse, y, mask, count)


def _fused_ce_bwd(ignore_index, res, g):
    hidden, wte, logits, lse, y, mask, count = res
    dtype = hidden.dtype
    coef = (g / count) * mask.astype(jnp.float32)               # [B, T]
    # softmax term, built in bf16 straight from the saved logits — the
    # only [B, T, V] tensor the backward materialises
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    dlogits = (p * coef[..., None]).astype(dtype)               # [B, T, V]
    dh = jnp.einsum("btv,vd->btd", dlogits, wte.astype(dtype))
    dw = jnp.einsum("btv,btd->vd", dlogits, hidden)
    # the -onehot(y) term never touches [B, T, V]: for dh it's a row
    # gather of wte, for dw an embedding-style segment-sum over targets
    wcoef = coef.astype(dtype)[..., None]
    dh = dh - wcoef * wte.astype(dtype)[y]
    dw = dw.at[y.reshape(-1)].add(
        -(wcoef * hidden).reshape(-1, hidden.shape[-1]))
    return dh.astype(hidden.dtype), dw.astype(wte.dtype), None


fused_cross_entropy.defvjp(_fused_ce_fwd, _fused_ce_bwd)
