"""ray_tpu.ops — Pallas TPU kernels for the hot ops.

The compute path is JAX/XLA; these kernels cover the cases where XLA's
fusion leaves HBM bandwidth on the table (attention score materialisation
being the big one). Reference counterpart: the CUDA kernels the reference
ships for the same ops (e.g. fused attention in its model runners) —
re-designed here for the TPU memory hierarchy (HBM -> VMEM -> MXU/VPU)
rather than translated.
"""

from ray_tpu.ops.flash_attention import flash_attention  # noqa: F401
from ray_tpu.ops.fused_ce import fused_cross_entropy  # noqa: F401
