"""Job submission: run driver scripts ON the cluster, track their state.

Reference: `dashboard/modules/job/{job_manager,job_supervisor,sdk}.py` —
a `JobSupervisor` detached actor wraps the driver subprocess; submission
state lives in the GCS (KV here, job table there). No separate dashboard
process: the supervisor is an ordinary detached actor reachable from any
client of the cluster.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

_KV_NS = "job_submission"

# terminal + live states (reference: JobStatus enum)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class _JobSupervisor:
    """Detached actor: runs the entrypoint as a subprocess on its node,
    captures output, publishes status to the GCS KV (reference:
    job_supervisor.py)."""

    def __init__(self, job_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]] = None):
        import os
        import subprocess
        import threading

        self._job_id = job_id
        self._entrypoint = entrypoint
        self._log: List[str] = []
        self._status = RUNNING
        self._returncode: Optional[int] = None
        env = dict(os.environ)
        env.update(env_vars or {})
        # the driver joins THIS cluster
        from ray_tpu._private.worker_api import _require_state

        env["RAY_TPU_ADDRESS"] = _require_state().core_worker.gcs_addr
        # the framework package must resolve in the subprocess no matter
        # its cwd/script dir (the session dir /tmp/ray_tpu would
        # otherwise shadow it as a namespace package!)
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + \
            env.get("PYTHONPATH", "")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        self._publish()

        def pump():
            for line in self._proc.stdout:
                self._log.append(line)
                if len(self._log) > 10_000:
                    del self._log[:1000]
            self._returncode = self._proc.wait()
            if self._status != STOPPED:
                self._status = SUCCEEDED if self._returncode == 0 \
                    else FAILED
            self._publish()

        threading.Thread(target=pump, daemon=True).start()

    def _publish(self):
        from ray_tpu._private.worker_api import _require_state

        cw = _require_state().core_worker
        cw._run_sync(cw.gcs.call("kv_put", {
            "ns": _KV_NS,
            "key": self._job_id.encode(),
            "value": json.dumps({
                "job_id": self._job_id,
                "entrypoint": self._entrypoint,
                "status": self._status,
                "returncode": self._returncode,
                "ts": time.time(),
            }).encode(),
        }))

    def status(self) -> Dict[str, Any]:
        return {"job_id": self._job_id, "status": self._status,
                "returncode": self._returncode}

    def logs(self, tail: int = 1000) -> str:
        return "".join(self._log[-tail:])

    def stop(self) -> bool:
        if self._proc.poll() is None:
            self._status = STOPPED
            self._proc.terminate()
            self._publish()
            return True
        return False

    def ping(self) -> bool:
        return True


class JobSubmissionClient:
    """Reference: `python/ray/dashboard/modules/job/sdk.py`
    JobSubmissionClient — same verbs (submit/status/logs/stop/list),
    actor-backed instead of REST."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   env_vars: Optional[Dict[str, str]] = None) -> str:
        job_id = submission_id or f"job-{uuid.uuid4().hex[:10]}"
        if submission_id is not None:
            # reference parity: an explicit submission_id that collides
            # with a recorded job is a caller error, not a silent
            # overwrite of the old job's record
            from ray_tpu._private.worker_api import _require_state

            cw = _require_state().core_worker
            reply = cw._run_sync(cw.gcs.call("kv_exists", {
                "ns": _KV_NS, "key": submission_id.encode()}))
            if reply["exists"]:
                raise ValueError(
                    f"job {submission_id!r} was already submitted")
        supervisor_cls = ray_tpu.remote(_JobSupervisor)
        supervisor_cls.options(
            name=f"_job_supervisor_{job_id}",
            lifetime="detached", num_cpus=0,
        ).remote(job_id, entrypoint, env_vars)
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"_job_supervisor_{job_id}")

    def get_job_status(self, job_id: str) -> str:
        try:
            sup = self._supervisor(job_id)
            return ray_tpu.get(sup.status.remote(), timeout=30)["status"]
        except Exception:  # noqa: BLE001 — supervisor gone: read the KV
            rec = self._kv_record(job_id)
            return rec["status"] if rec else FAILED

    def get_job_info(self, job_id: str) -> Optional[Dict[str, Any]]:
        return self._kv_record(job_id)

    def get_job_logs(self, job_id: str, tail: int = 1000) -> str:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.logs.remote(tail), timeout=30)

    def stop_job(self, job_id: str) -> bool:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.stop.remote(), timeout=30)

    def wait_until_finished(self, job_id: str,
                            timeout: float = 600.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (SUCCEEDED, FAILED, STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")

    def delete_job(self, job_id: str) -> bool:
        """Drop a terminal job's KV record (reference SDK verb).
        Refuses while the job may still be running — stop it first."""
        from ray_tpu._private.worker_api import _require_state

        status = self.get_job_status(job_id)
        if status not in (SUCCEEDED, FAILED, STOPPED):
            raise RuntimeError(
                f"job {job_id!r} is {status}; stop it before deleting")
        cw = _require_state().core_worker
        reply = cw._run_sync(cw.gcs.call("kv_del", {
            "ns": _KV_NS, "key": job_id.encode()}))
        return bool(reply["deleted"])

    def list_jobs(self) -> List[Dict[str, Any]]:
        from ray_tpu._private.worker_api import _require_state

        cw = _require_state().core_worker
        keys = cw._run_sync(
            cw.gcs.call("kv_keys", {"ns": _KV_NS}))["keys"]
        out = []
        for key in keys:
            rec = self._kv_record(
                key.decode() if isinstance(key, bytes) else key)
            if rec:
                out.append(rec)
        return out

    def _kv_record(self, job_id: str) -> Optional[Dict[str, Any]]:
        from ray_tpu._private.worker_api import _require_state

        cw = _require_state().core_worker
        reply = cw._run_sync(cw.gcs.call("kv_get", {
            "ns": _KV_NS, "key": job_id.encode()}))
        if reply["value"] is None:
            return None
        return json.loads(reply["value"])
