"""Experimental APIs (reference: `python/ray/experimental/`)."""

from ray_tpu.experimental.channel import (  # noqa: F401
    TAG_ERR,
    TAG_OK,
    ChannelClosedError,
    FrameScratch,
    ShmChannel,
)
