"""Mutable shared-memory channels between actor processes.

Reference: the compiled-graph (aDAG) channel layer —
`src/ray/core_worker/experimental_mutable_object_manager.h:37` and
`python/ray/experimental/channel/shared_memory_channel.py`. A channel is
a PRE-ALLOCATED single-writer/single-reader shm buffer reused across
executions: writing a new value mutates the buffer in place and bumps a
sequence number instead of creating an object + submitting a task, which
is what makes a compiled DAG's steady-state latency land in microseconds
instead of the task-submission path's hundreds.

Synchronization is a seqlock-style pair of 8-byte counters (write_seq
advanced only by the writer, read_seq only by the reader) — no
cross-process mutex, so a crashed peer can never leave the lock held.
The payload store happens before the seq bump in program order; on
x86-64's total-store-order memory model the reader observing the new
seq therefore observes the payload. (A weakly-ordered ISA would need
explicit fences here; TPU-VM hosts are x86-64.)

Waiting is NOT a poll loop: each channel carries two advisory-wakeup
FIFOs next to its shm segment (`<name>.rdy` wakes the reader after a
publish, `<name>.fre` wakes the writer after a release). A waiter
re-checks the seq pair, then blocks in select() on its FIFO; the peer
writes a token AFTER updating its counter, so the select returns
immediately — a kernel-directed wakeup instead of a timeslice lottery.
On a busy single-core host this is the difference between ~7 µs and
>1 ms per hop: sched_yield-style backoff leaves the handoff to CFS,
which parks spinners for whole timeslices. Tokens are advisory (extra
tokens cause one spurious re-check, and a bounded select timeout
re-checks the shutdown flag), so a crashed peer still can't wedge the
channel. Hosts without FIFO support fall back to the old spin->sleep
backoff.

Channels are same-node by construction (POSIX shm). The TPU-native
analogue for device arrays is jit fusion with buffer donation — see
ray_tpu/dag.py `jax_stage` — where XLA owns the transfers over ICI;
these channels are the host-side control/data plane for actor graphs.

Frame format (the zero-pickle hot path): a frame is a fixed raw header
— tag byte, 8-byte LE seq — followed by the payload bytes, written in
place into the shm buffer. Readers parse tag and seq straight from the
header, so a stale frame (driver timed out and bumped its execution
counter) is discarded by releasing the slot WITHOUT deserializing the
payload; only a current frame's payload is unpickled, zero-copy, from a
memoryview over the shm segment. Writers serialize once into a reusable
`FrameScratch` and memcpy the same view into every consumer edge — no
per-call `pickle.dumps` allocation, no (tag, seq, value) tuple.
"""

from __future__ import annotations

import os
import pickle
import select
import tempfile
import time
import uuid
from multiprocessing import shared_memory
from typing import Optional, Tuple

_HEADER = 32  # write_seq | read_seq | length | flags — 4 x 8 bytes LE
_FLAG_SHUTDOWN = 1

_FRAME = 16   # tag (1 byte) | pad (7) | seq (8 bytes LE)
TAG_OK = 0
TAG_ERR = 1

# bounded select() slice: a waiter re-checks the shutdown flag at least
# this often even if a wakeup token is lost (crashed peer)
_BLOCK_SLICE = 0.05


def _fifo_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") \
        else tempfile.gettempdir()


class _ChannelStats:
    """Process-wide frame-plane counters (flight-recorder plane).

    Hot-path cost is plain integer increments (~100 ns on a ~37 µs
    hop); blocked-wait time is only measured when a wait actually
    parks, so the fast path pays nothing for it. Exposed as a
    scrape-time /metrics callback — no metric objects are constructed
    per call (see raylint `metric-in-hot-loop`)."""

    __slots__ = ("frames_written", "frames_read", "stale_skips",
                 "write_wait_ns", "read_wait_ns", "wakeup_tokens")

    def __init__(self):
        self.frames_written = 0
        self.frames_read = 0
        self.stale_skips = 0
        self.write_wait_ns = 0
        self.read_wait_ns = 0
        self.wakeup_tokens = 0

    def as_dict(self) -> dict:
        return {
            "frames_written": self.frames_written,
            "frames_read": self.frames_read,
            "stale_skips": self.stale_skips,
            "write_wait_ms": round(self.write_wait_ns / 1e6, 3),
            "read_wait_ms": round(self.read_wait_ns / 1e6, 3),
            "wakeup_tokens": self.wakeup_tokens,
        }


CHANNEL_STATS = _ChannelStats()


def channel_stats() -> dict:
    return CHANNEL_STATS.as_dict()


def note_stale_skip() -> None:
    """A stale frame was released from its raw header without
    deserializing the payload (driver timeout recovery)."""
    CHANNEL_STATS.stale_skips += 1


def _stats_metrics_text() -> str:
    s = CHANNEL_STATS
    return (
        "# TYPE channel_frames_total counter\n"
        f'channel_frames_total{{op="write"}} {s.frames_written}\n'
        f'channel_frames_total{{op="read"}} {s.frames_read}\n'
        "# TYPE channel_stale_skips_total counter\n"
        f"channel_stale_skips_total {s.stale_skips}\n"
        "# TYPE channel_wait_ms_total counter\n"
        f'channel_wait_ms_total{{side="write"}} '
        f"{round(s.write_wait_ns / 1e6, 3)}\n"
        f'channel_wait_ms_total{{side="read"}} '
        f"{round(s.read_wait_ns / 1e6, 3)}\n")


def _register_metrics() -> None:
    from ray_tpu.util import metrics as _metrics

    _metrics.DEFAULT_REGISTRY.register_callback(
        "channel_frames", _stats_metrics_text)


_register_metrics()


class ChannelClosedError(RuntimeError):
    """The channel was shut down by its owner (compiled DAG teardown)."""


def _pause(spins: int) -> None:
    # Fallback for hosts without FIFO wakeups. Tuned for the
    # sub-millisecond round-trip regime: stay on the zero-sleep probe
    # longer and cap the parked sleep at 200 µs — the old 1 ms cap
    # could bill a frame that arrived just after parking half a
    # round-trip's worth of idle time.
    if spins < 400:
        time.sleep(0)  # yield the GIL, stay hot
    else:
        time.sleep(min(2e-4, 1e-5 * (spins - 399)))


class FrameScratch:
    """Reusable serialization buffer: pickle a value once, hand out a
    zero-copy view to write into any number of edges. Grows
    geometrically and is never shrunk, so a steady-state pipeline does
    no per-call allocation at all."""

    __slots__ = ("_buf", "_len")

    def __init__(self, initial: int = 1024):
        self._buf = bytearray(initial)
        self._len = 0

    def write(self, data) -> int:
        """File-like sink for pickle.Pickler."""
        n = len(data)
        end = self._len + n
        if end > len(self._buf):
            grow = max(end, 2 * len(self._buf))
            self._buf.extend(b"\x00" * (grow - len(self._buf)))
        self._buf[self._len:end] = data
        self._len = end
        return n

    def pack(self, value) -> memoryview:
        """Serialize `value` into the scratch; the returned view is valid
        until the next pack()."""
        self._len = 0
        pickle.Pickler(self, protocol=pickle.HIGHEST_PROTOCOL).dump(value)
        return memoryview(self._buf)[:self._len]


class ShmChannel:
    """Single-writer single-reader mutable buffer (capacity fixed at
    creation). `write` blocks until the reader consumed the previous
    value (depth-1 backpressure — the aDAG execution semantics: one
    in-flight value per edge)."""

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool,
                 name: Optional[str] = None):
        self._shm = shm
        self._owner = owner
        self._buf = shm.buf
        self._name = name or shm.name.lstrip("/")
        self._rdy_fd: Optional[int] = None  # tokens: data published
        self._fre_fd: Optional[int] = None  # tokens: slot released
        self._fifo_paths: Tuple[str, ...] = ()
        self._open_fifos()

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int = 8 << 20) -> "ShmChannel":
        shm = shared_memory.SharedMemory(name=name, create=True,
                                         size=_HEADER + capacity)
        shm.buf[:_HEADER] = b"\x00" * _HEADER
        return cls(shm, owner=True, name=name)

    @classmethod
    def attach(cls, name: str) -> "ShmChannel":
        return cls(shared_memory.SharedMemory(name=name), owner=False,
                   name=name)

    @staticmethod
    def make_name(index: int) -> str:
        return f"rtpu_ch_{os.getpid()}_{uuid.uuid4().hex[:12]}_{index}"

    def _open_fifos(self) -> None:
        """Best-effort wakeup FIFOs beside the shm segment; on any
        failure the channel silently degrades to the spin fallback."""
        paths = []
        fds = []
        try:
            base = os.path.join(_fifo_dir(), self._name)
            for suffix in (".rdy", ".fre"):
                path = base + suffix
                try:
                    os.mkfifo(path)
                except FileExistsError:
                    pass
                paths.append(path)
                # O_RDWR: never blocks on open and keeps the FIFO alive
                # with a single endpoint attached
                fds.append(os.open(path, os.O_RDWR | os.O_NONBLOCK))
        except (OSError, AttributeError, NotImplementedError):
            for fd in fds:
                try:
                    os.close(fd)
                except OSError:
                    pass
            return
        self._rdy_fd, self._fre_fd = fds
        self._fifo_paths = tuple(paths)

    def _token(self, fd: Optional[int]) -> None:
        if fd is None:
            return
        try:
            os.write(fd, b"\x00")
        except (BlockingIOError, OSError):
            pass  # full FIFO still wakes the peer; closed fd is benign

    def _block(self, fd: Optional[int], spins: int,
               deadline: Optional[float]) -> None:
        """Wait for a wakeup token (or fall back to the spin pause),
        bounded so shutdown/timeout are always re-checked."""
        if fd is None:
            _pause(spins)
            return
        timeout = _BLOCK_SLICE
        if deadline is not None:
            timeout = min(timeout, max(0.0, deadline - time.monotonic()))
        try:
            select.select([fd], [], [], timeout)
            os.read(fd, 4096)  # drain: tokens are advisory, level-check
        except (BlockingIOError, OSError, ValueError):
            pass

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except (OSError, BufferError):
            pass
        for fd in (self._rdy_fd, self._fre_fd):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._rdy_fd = self._fre_fd = None

    def destroy(self) -> None:
        """Owner side: signal shutdown, then unlink the segment."""
        try:
            self._set(3, _FLAG_SHUTDOWN)
        except (TypeError, ValueError):
            pass  # already closed
        # wake any peer parked in select() so it sees the flag now
        self._token(self._rdy_fd)
        self._token(self._fre_fd)
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass
        for path in self._fifo_paths:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- header ------------------------------------------------------------

    def _get(self, slot: int) -> int:
        return int.from_bytes(self._buf[slot * 8:(slot + 1) * 8], "little")

    def _set(self, slot: int, value: int) -> None:
        self._buf[slot * 8:(slot + 1) * 8] = value.to_bytes(8, "little")

    @property
    def capacity(self) -> int:
        return len(self._buf) - _HEADER

    def signal_shutdown(self) -> None:
        self._set(3, self._get(3) | _FLAG_SHUTDOWN)
        self._token(self._rdy_fd)
        self._token(self._fre_fd)

    def _check_open(self) -> None:
        if self._get(3) & _FLAG_SHUTDOWN:
            raise ChannelClosedError("channel was shut down")

    # -- data path ---------------------------------------------------------

    def _wait_writable(self, timeout: Optional[float]) -> None:
        """Block until the depth-1 slot is free (previous value
        consumed). Parked time is charged to CHANNEL_STATS only when the
        wait actually loops — the already-free fast path pays nothing."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        waited_from = None
        while self._get(0) != self._get(1):
            if waited_from is None:
                waited_from = time.perf_counter_ns()
            self._check_open()
            if deadline is not None and time.monotonic() > deadline:
                CHANNEL_STATS.write_wait_ns += (
                    time.perf_counter_ns() - waited_from)
                raise TimeoutError("channel write timed out")
            self._block(self._fre_fd, spins, deadline)
            spins += 1
        if waited_from is not None:
            CHANNEL_STATS.write_wait_ns += (
                time.perf_counter_ns() - waited_from)
        self._check_open()

    def _wait_readable(self, timeout: Optional[float]) -> None:
        """Block until a value is published."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        waited_from = None
        while self._get(0) == self._get(1):
            if waited_from is None:
                waited_from = time.perf_counter_ns()
            self._check_open()
            if deadline is not None and time.monotonic() > deadline:
                CHANNEL_STATS.read_wait_ns += (
                    time.perf_counter_ns() - waited_from)
                raise TimeoutError("channel read timed out")
            self._block(self._rdy_fd, spins, deadline)
            spins += 1
        if waited_from is not None:
            CHANNEL_STATS.read_wait_ns += (
                time.perf_counter_ns() - waited_from)

    def write(self, data: bytes, timeout: Optional[float] = None) -> None:
        if len(data) > self.capacity:
            raise ValueError(
                f"value of {len(data)} bytes exceeds channel capacity "
                f"{self.capacity}")
        self._wait_writable(timeout)
        self._buf[_HEADER:_HEADER + len(data)] = data
        self._set(2, len(data))
        self._set(0, self._get(0) + 1)  # publish AFTER the payload store
        self._token(self._rdy_fd)

    def read(self, timeout: Optional[float] = None) -> bytes:
        self._wait_readable(timeout)
        n = self._get(2)
        data = bytes(self._buf[_HEADER:_HEADER + n])
        self._set(1, self._get(1) + 1)  # release the slot to the writer
        self._token(self._fre_fd)
        return data

    # -- frame path (zero-pickle compiled-DAG hot loop) --------------------

    def write_frame(self, tag: int, seq: int, payload,
                    timeout: Optional[float] = None) -> None:
        """Write a raw-header frame: tag byte + 8-byte seq, then the
        payload bytes copied in place from `payload` (any buffer —
        typically a FrameScratch view, so a fan-out producer serializes
        once and memcpys per edge)."""
        n = len(payload)
        if _FRAME + n > self.capacity:
            raise ValueError(
                f"frame of {n} payload bytes exceeds channel capacity "
                f"{self.capacity - _FRAME}")
        self._wait_writable(timeout)
        buf = self._buf
        buf[_HEADER] = tag
        buf[_HEADER + 8:_HEADER + 16] = seq.to_bytes(8, "little")
        buf[_HEADER + _FRAME:_HEADER + _FRAME + n] = payload
        self._set(2, _FRAME + n)
        self._set(0, self._get(0) + 1)  # publish AFTER the payload store
        self._token(self._rdy_fd)
        CHANNEL_STATS.frames_written += 1

    def read_frame(
            self, timeout: Optional[float] = None
    ) -> Tuple[int, int, memoryview]:
        """Block until a frame is available and return (tag, seq,
        payload_view) with tag and seq parsed from the raw header — the
        payload is NOT deserialized. The view aliases the shm buffer:
        the caller inspects seq, unpickles the view only when current,
        and MUST call release_frame() afterwards (a stale frame is
        released without ever touching the payload)."""
        self._wait_readable(timeout)
        buf = self._buf
        n = self._get(2)
        tag = buf[_HEADER]
        seq = int.from_bytes(buf[_HEADER + 8:_HEADER + 16], "little")
        CHANNEL_STATS.frames_read += 1
        return tag, seq, buf[_HEADER + _FRAME:_HEADER + n]

    def release_frame(self) -> None:
        """Release the slot of the last read_frame() to the writer. Any
        payload view from that read_frame() is dead after this call."""
        self._set(1, self._get(1) + 1)
        self._token(self._fre_fd)
