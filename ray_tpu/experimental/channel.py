"""Mutable shared-memory channels between actor processes.

Reference: the compiled-graph (aDAG) channel layer —
`src/ray/core_worker/experimental_mutable_object_manager.h:37` and
`python/ray/experimental/channel/shared_memory_channel.py`. A channel is
a PRE-ALLOCATED single-writer/single-reader shm buffer reused across
executions: writing a new value mutates the buffer in place and bumps a
sequence number instead of creating an object + submitting a task, which
is what makes a compiled DAG's steady-state latency land in microseconds
instead of the task-submission path's hundreds.

Synchronization is a seqlock-style pair of 8-byte counters (write_seq
advanced only by the writer, read_seq only by the reader) polled with an
adaptive spin->sleep backoff — no cross-process mutex, so a crashed peer
can never leave the lock held. The payload store happens before the seq
bump in program order; on x86-64's total-store-order memory model the
reader observing the new seq therefore observes the payload. (A weakly-
ordered ISA would need explicit fences here; TPU-VM hosts are x86-64.)

Channels are same-node by construction (POSIX shm). The TPU-native
analogue for device arrays is jit fusion with buffer donation — see
ray_tpu/dag.py `jax_stage` — where XLA owns the transfers over ICI;
these channels are the host-side control/data plane for actor graphs.
"""

from __future__ import annotations

import os
import time
import uuid
from multiprocessing import shared_memory
from typing import Optional

_HEADER = 32  # write_seq | read_seq | length | flags — 4 x 8 bytes LE
_FLAG_SHUTDOWN = 1


class ChannelClosedError(RuntimeError):
    """The channel was shut down by its owner (compiled DAG teardown)."""


def _pause(spins: int) -> None:
    if spins < 200:
        time.sleep(0)  # yield the GIL/core, stay hot
    else:
        time.sleep(min(0.001, 2e-5 * (spins - 199)))


class ShmChannel:
    """Single-writer single-reader mutable buffer (capacity fixed at
    creation). `write` blocks until the reader consumed the previous
    value (depth-1 backpressure — the aDAG execution semantics: one
    in-flight value per edge)."""

    def __init__(self, shm: shared_memory.SharedMemory, owner: bool):
        self._shm = shm
        self._owner = owner
        self._buf = shm.buf

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int = 8 << 20) -> "ShmChannel":
        shm = shared_memory.SharedMemory(name=name, create=True,
                                         size=_HEADER + capacity)
        shm.buf[:_HEADER] = b"\x00" * _HEADER
        return cls(shm, owner=True)

    @classmethod
    def attach(cls, name: str) -> "ShmChannel":
        return cls(shared_memory.SharedMemory(name=name), owner=False)

    @staticmethod
    def make_name(index: int) -> str:
        return f"rtpu_ch_{os.getpid()}_{uuid.uuid4().hex[:12]}_{index}"

    def close(self) -> None:
        try:
            self._buf = None
            self._shm.close()
        except (OSError, BufferError):
            pass

    def destroy(self) -> None:
        """Owner side: signal shutdown, then unlink the segment."""
        try:
            self._set(3, _FLAG_SHUTDOWN)
        except (TypeError, ValueError):
            pass  # already closed
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass

    # -- header ------------------------------------------------------------

    def _get(self, slot: int) -> int:
        return int.from_bytes(self._buf[slot * 8:(slot + 1) * 8], "little")

    def _set(self, slot: int, value: int) -> None:
        self._buf[slot * 8:(slot + 1) * 8] = value.to_bytes(8, "little")

    @property
    def capacity(self) -> int:
        return len(self._buf) - _HEADER

    def signal_shutdown(self) -> None:
        self._set(3, self._get(3) | _FLAG_SHUTDOWN)

    def _check_open(self) -> None:
        if self._get(3) & _FLAG_SHUTDOWN:
            raise ChannelClosedError("channel was shut down")

    # -- data path ---------------------------------------------------------

    def write(self, data: bytes, timeout: Optional[float] = None) -> None:
        if len(data) > self.capacity:
            raise ValueError(
                f"value of {len(data)} bytes exceeds channel capacity "
                f"{self.capacity}")
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        # depth-1 ring: previous value must be consumed first
        while self._get(0) != self._get(1):
            self._check_open()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out")
            _pause(spins)
            spins += 1
        self._check_open()
        self._buf[_HEADER:_HEADER + len(data)] = data
        self._set(2, len(data))
        self._set(0, self._get(0) + 1)  # publish AFTER the payload store

    def read(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while self._get(0) == self._get(1):
            self._check_open()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            _pause(spins)
            spins += 1
        n = self._get(2)
        data = bytes(self._buf[_HEADER:_HEADER + n])
        self._set(1, self._get(1) + 1)  # release the slot to the writer
        return data
