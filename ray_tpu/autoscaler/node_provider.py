"""Node providers: how the autoscaler launches and kills machines.

Reference: `python/ray/autoscaler/node_provider.py:13` (the pluggable
NodeProvider ABC — AWS/GCP/... implementations) and the test harness
`python/ray/autoscaler/_private/fake_multi_node/node_provider.py`, which
realizes "cloud nodes" as local processes. The TPU deployment analogue
of a node type is a pod slice: a node type may declare `slice_type` and
`num_hosts`, and creating one instance brings up every host of a slice
(the gang the scheduler places on atomically).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class NodeType:
    """A launchable shape (reference: available_node_types in the
    cluster YAML)."""

    name: str
    resources: Dict[str, float]
    max_workers: int = 10
    # TPU pod-slice node types: one instance = num_hosts raylets
    # carrying slice labels (scheduling.place_slice_bundles gang-places
    # onto them)
    slice_type: Optional[str] = None
    num_hosts: int = 1


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    node_ids: List[str]  # hex raylet node ids (slice: one per host)


class NodeProvider:
    """ABC. Implementations own machine lifecycle only — joining the
    cluster is the raylet's own registration path."""

    def create_node(self, node_type: NodeType) -> Instance:
        raise NotImplementedError

    def terminate_node(self, instance: Instance) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[Instance]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Fake cloud: every instance is a local raylet process (or a group
    of them for slice types) joined to an existing GCS — the test
    mechanism for autoscaling logic without machines."""

    def __init__(self, cluster):
        # `cluster` is a ray_tpu._private.node.Cluster owning the GCS
        self._cluster = cluster
        self._instances: Dict[str, Instance] = {}
        self._handles: Dict[str, list] = {}
        self._counter = 0

    def create_node(self, node_type: NodeType) -> Instance:
        self._counter += 1
        iid = f"fake-{node_type.name}-{self._counter}"
        if node_type.slice_type:
            handles = self._cluster.add_slice(
                node_type.slice_type, node_type.num_hosts,
                chips_per_host=int(
                    node_type.resources.get("TPU", 4)),
                cpus_per_host=node_type.resources.get("CPU", 1.0),
                name=iid)
        else:
            handles = [self._cluster.add_node(dict(node_type.resources))]
        inst = Instance(iid, node_type.name,
                        [h.node_id_hex for h in handles])
        self._instances[iid] = inst
        self._handles[iid] = handles
        return inst

    def terminate_node(self, instance: Instance) -> None:
        for handle in self._handles.pop(instance.instance_id, []):
            if handle in self._cluster.nodes:
                self._cluster.remove_node(handle)
        self._instances.pop(instance.instance_id, None)

    def non_terminated_nodes(self) -> List[Instance]:
        return list(self._instances.values())
