"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference: `python/ray/autoscaler/` (v1 StandardAutoscaler + v2
reconciler; SURVEY.md §2.8). Slice-aware: TPU pod slices scale up and
down as atomic multi-host instances.
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler
from ray_tpu.autoscaler.gcp_tpu import (
    GCEMetadataTransport,
    TPUQueuedResourceProvider,
    bootstrap_script,
)
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider,
    Instance,
    NodeProvider,
    NodeType,
)

__all__ = ["Autoscaler", "FakeMultiNodeProvider", "GCEMetadataTransport",
           "Instance", "NodeProvider", "NodeType",
           "TPUQueuedResourceProvider", "bootstrap_script"]
