"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference: `python/ray/autoscaler/` (v1 StandardAutoscaler + v2
reconciler; SURVEY.md §2.8). Slice-aware: TPU pod slices scale up and
down as atomic multi-host instances.
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider,
    Instance,
    NodeProvider,
    NodeType,
)

__all__ = ["Autoscaler", "FakeMultiNodeProvider", "Instance",
           "NodeProvider", "NodeType"]
