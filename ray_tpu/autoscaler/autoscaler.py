"""Load-based autoscaler: reconcile cluster size against pending demand.

Reference: `python/ray/autoscaler/_private/autoscaler.py:172`
(StandardAutoscaler.update) and the v2 redesign
(`autoscaler/v2/instance_manager/reconciler.py`, bin-packing in
`v2/scheduler.py:624` ResourceDemandScheduler): each round reads demand
from the GCS (pending leases + pending placement groups), bin-packs the
unmet part onto hypothetical nodes of the configured types, launches the
difference through a NodeProvider, and retires provider-owned nodes that
have sat idle past the timeout.

TPU-first: a pending slice-topology placement group demands one whole
slice instance (`NodeType.slice_type`), never loose hosts — keeping
scale-up aligned with the scheduler's atomic gang placement.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.rpc import ClientPool
from ray_tpu.autoscaler.node_provider import Instance, NodeProvider, NodeType

logger = logging.getLogger(__name__)


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _consume(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    def __init__(self, gcs_addr: str, provider: NodeProvider,
                 node_types: List[NodeType],
                 max_workers: int = 8,
                 idle_timeout_s: float = 60.0,
                 update_interval_s: float = 2.0,
                 boot_timeout_s: float = 900.0):
        self.gcs_addr = gcs_addr
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.update_interval_s = update_interval_s
        self.boot_timeout_s = boot_timeout_s
        self._clients = ClientPool()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serializes reconcile rounds: the background loop and direct
        # update() callers (tests, drivers poking the scaler after a
        # fault) must not interleave a snapshot with another round's
        # provisioning — that is one half of the double-replacement bug
        self._reconcile_lock = threading.Lock()
        #: instance_id -> max host count ever seen registered; a drop
        #: below it means a host DIED (vs never booted) — the slice is
        #: broken, not booting
        self._seen_up: Dict[str, int] = {}
        #: instance_id -> first time this reconciler saw it; an instance
        #: that never fully registers within boot_timeout_s is broken
        #: (failed bootstrap) and must be replaced, not credited forever
        self._first_seen: Dict[str, float] = {}

    # -- one reconcile round (directly callable from tests) ------------

    def update(self) -> Dict[str, int]:
        """Run one reconcile round; returns {"launched": n, "terminated": m}."""
        with self._reconcile_lock:
            return asyncio.run(self._update_async())

    async def _update_async(self) -> Dict[str, int]:
        gcs = await self._clients.get(self.gcs_addr)
        load = await gcs.call("get_cluster_load", {}, timeout=30.0)
        launched = self._scale_up(load)
        terminated = self._scale_down(load)
        await self._clients.close_all()
        return {"launched": launched, "terminated": terminated}

    @staticmethod
    def _node_id(n: dict) -> str:
        nid = n["node_id"]
        return nid.hex() if isinstance(nid, bytes) else nid

    def _instance_hosts(self, inst: Instance, ntype: Optional[NodeType],
                        nodes: List[dict]) -> tuple:
        """(known host node-ids, expected host count) for an instance.

        Local/fake providers know their raylet ids up front; cloud
        providers (TPU queued resources) report none — their hosts are
        matched by the `autoscaler_instance` label each raylet registers
        with from its bootstrap script."""
        if inst.node_ids:
            return list(inst.node_ids), len(inst.node_ids)
        from ray_tpu.autoscaler.gcp_tpu import INSTANCE_LABEL
        matched = [self._node_id(n) for n in nodes
                   if n.get("labels", {}).get(INSTANCE_LABEL)
                   == inst.instance_id]
        expected = ntype.num_hosts if ntype is not None else 1
        return matched, expected

    def _scale_up(self, load: dict) -> int:
        # hypothetical free capacity: registered nodes' availability...
        avail_pool = [dict(n["available"]) for n in load["nodes"]]
        registered = {self._node_id(n) for n in load["nodes"]}
        instances = self.provider.non_terminated_nodes()
        # ids at snapshot time — the staleness re-check before
        # provisioning compares against this set
        snapshot_ids = {i.instance_id for i in instances}
        # slice_type -> number of instances still booting: each booting
        # slice absorbs exactly ONE pending topology demand (a set here
        # would collapse N concurrently-provisioning slices into one and
        # relaunch every round for the rest)
        booting_slices: Dict[str, int] = {}
        inst_hosts: Dict[str, tuple] = {}
        for inst in list(instances):
            ntype = self.node_types.get(inst.node_type)
            hosts, expected = self._instance_hosts(inst, ntype,
                                                   load["nodes"])
            inst_hosts[inst.instance_id] = (hosts, expected)
            if ntype is None:
                continue
            up = sum(1 for nid in hosts if nid in registered)
            seen = self._seen_up.get(inst.instance_id, 0)
            first = self._first_seen.setdefault(inst.instance_id,
                                                time.monotonic())
            # lost-host check FIRST: a slice that fully booted and later
            # dropped a host is BROKEN, and must not be mis-diagnosed as
            # "never booted" merely because it outlived boot_timeout_s
            if up >= seen and up < expected and \
                    time.monotonic() - first > self.boot_timeout_s:
                # bootstrap never (fully) joined within the timeout: a
                # failed startup script would otherwise absorb its
                # demand as "booting" credit forever
                logger.warning(
                    "instance %s never fully booted (%d/%d hosts after "
                    "%.0fs); terminating", inst.instance_id, up,
                    expected, time.monotonic() - first)
                self.provider.terminate_node(inst)
                self._seen_up.pop(inst.instance_id, None)
                self._first_seen.pop(inst.instance_id, None)
                instances.remove(inst)
                inst_hosts.pop(inst.instance_id, None)
                continue
            if up < seen:
                # a previously-registered host died: the slice is
                # BROKEN, not booting. Terminate it so the gang's demand
                # relaunches a fresh slice instead of waiting forever on
                # phantom capacity (slices are atomic — a 15/16 slice
                # can't place its gang anyway).
                logger.warning(
                    "instance %s lost a host (%d -> %d of %d); "
                    "terminating the broken slice", inst.instance_id,
                    seen, up, expected)
                self.provider.terminate_node(inst)
                self._seen_up.pop(inst.instance_id, None)
                self._first_seen.pop(inst.instance_id, None)
                instances.remove(inst)
                inst_hosts.pop(inst.instance_id, None)
                continue
            self._seen_up[inst.instance_id] = max(seen, up)
            for _ in range(max(0, expected - up)):
                # ...plus launched-but-still-booting capacity: a
                # slow-booting real node must absorb the demand that
                # caused its launch, or every round re-launches for
                # the same pending work
                avail_pool.append(dict(ntype.resources))
            if ntype.slice_type and up < expected:
                booting_slices[ntype.slice_type] = \
                    booting_slices.get(ntype.slice_type, 0) + 1
        # prune terminated instances from the tracking memories
        live = {i.instance_id for i in instances}
        for d in (self._seen_up, self._first_seen):
            for iid in list(d):
                if iid not in live:
                    del d[iid]

        demands: List[Dict[str, float]] = list(load["pending"])
        slice_demands: List[str] = []
        for pg in load["pending_pgs"]:
            if pg.get("topology"):
                slice_demands.append(pg["topology"])
            else:
                demands.extend(pg["bundles"])

        # caps are counted in HOSTS, globally and per type (reusing the
        # per-instance resolution computed above)
        host_count = sum(exp for _h, exp in inst_hosts.values())
        type_counts: Dict[str, int] = {}
        for inst in instances:
            type_counts[inst.node_type] = \
                type_counts.get(inst.node_type, 0) + 1
        planned_launches: List[NodeType] = []

        def may_launch(ntype: NodeType) -> bool:
            return (host_count + ntype.num_hosts <= self.max_workers
                    and type_counts.get(ntype.name, 0) <
                    ntype.max_workers)

        def record_launch(ntype: NodeType):
            nonlocal host_count
            host_count += ntype.num_hosts
            type_counts[ntype.name] = type_counts.get(ntype.name, 0) + 1

        # slice-topology PGs demand whole slice instances, atomically
        for topology in slice_demands:
            if booting_slices.get(topology, 0) > 0:
                booting_slices[topology] -= 1
                continue  # a slice for this demand is already booting
            ntype = next(
                (t for t in self.node_types.values()
                 if t.slice_type == topology), None)
            if ntype is None:
                logger.warning("no node type provides slice %s", topology)
                continue
            if not may_launch(ntype):
                continue
            logger.info("scaling up: slice %s (%d hosts)", topology,
                        ntype.num_hosts)
            planned_launches.append(ntype)
            record_launch(ntype)

        # bin-pack loose demands largest-first (reference:
        # ResourceDemandScheduler's utilization-based packing)
        demands.sort(key=lambda d: -sum(d.values()))
        planned: List[Dict[str, float]] = []
        planned_types: List[NodeType] = []
        for demand in demands:
            placed = False
            for avail in avail_pool + planned:
                if _fits(avail, demand):
                    _consume(avail, demand)
                    placed = True
                    break
            if placed:
                continue
            ntype = self._smallest_fitting_type(demand)
            if ntype is None:
                logger.warning("demand %s fits no node type", demand)
                continue
            if not may_launch(ntype):
                continue
            fresh = dict(ntype.resources)
            _consume(fresh, demand)
            planned.append(fresh)
            planned_types.append(ntype)
            record_launch(ntype)
            logger.info("scaling up: %s %s", ntype.name, ntype.resources)
            planned_launches.append(ntype)
        return self._provision(planned_launches, snapshot_ids)

    def _provision(self, planned: List[NodeType],
                   snapshot_ids: set) -> int:
        """Launch the planned nodes, after a STALENESS RE-CHECK on the
        provider listing: the plan was computed from a snapshot, and a
        node that joined since (a concurrent recovery path replacing a
        node killed mid-poll, an operator's manual launch) must absorb a
        planned launch of its type instead of being doubled. Without
        this, a kill landing between snapshot and provisioning is
        replaced twice — once by whoever reacted first and once by this
        round's stale plan (PR-2 controller pattern: re-validate state
        immediately before acting on it)."""
        if not planned:
            return 0
        fresh_counts: Dict[str, int] = {}
        for inst in self.provider.non_terminated_nodes():
            if inst.instance_id not in snapshot_ids:
                fresh_counts[inst.node_type] = \
                    fresh_counts.get(inst.node_type, 0) + 1
        launched = 0
        for ntype in planned:
            if fresh_counts.get(ntype.name, 0) > 0:
                fresh_counts[ntype.name] -= 1
                logger.info(
                    "skipping launch of %s: an instance of that type "
                    "appeared since the demand snapshot", ntype.name)
                continue
            self.provider.create_node(ntype)
            launched += ntype.num_hosts if ntype.slice_type else 1
        return launched

    def _smallest_fitting_type(self, demand: Dict[str, float]
                               ) -> Optional[NodeType]:
        fitting = [
            t for t in self.node_types.values()
            if t.slice_type is None and _fits(dict(t.resources), demand)
        ]
        if not fitting:
            return None
        return min(fitting, key=lambda t: sum(t.resources.values()))

    def _scale_down(self, load: dict) -> int:
        # any pending work keeps every node: the next round may pack it
        # onto a currently-idle node
        if load["pending"] or load["pending_pgs"]:
            return 0
        idle_ids = {
            n["node_id"].hex() if isinstance(n["node_id"], bytes)
            else n["node_id"]
            for n in load["nodes"]
            if n["idle_duration_s"] >= self.idle_timeout_s
        }
        terminated = 0
        for inst in list(self.provider.non_terminated_nodes()):
            ntype = self.node_types.get(inst.node_type)
            hosts, expected = self._instance_hosts(inst, ntype,
                                                   load["nodes"])
            # slices retire atomically: only when fully booted AND every
            # host is idle (a still-provisioning instance has work coming)
            if len(hosts) == expected and \
                    all(nid in idle_ids for nid in hosts):
                logger.info("scaling down idle instance %s",
                            inst.instance_id)
                self.provider.terminate_node(inst)
                terminated += len(hosts)
        return terminated

    # -- background loop ----------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:  # noqa: BLE001
                logger.exception("autoscaler round failed")
            self._stop.wait(self.update_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
