"""GCE TPU pod-slice node provider (queued-resources API).

Reference: `python/ray/autoscaler/node_provider.py:13` (the pluggable
NodeProvider ABC) + `python/ray/autoscaler/_private/gcp/node_provider.py`
(the GCP implementation) — re-designed TPU-first: the launchable unit is
a WHOLE pod slice via the TPU v2 `queuedResources` API (one create call
provisions every host of a v5e-16/v4-32/... slice atomically, matching
the scheduler's slice-atomic gang placement), not individual VMs.

Cloud access is injected: the provider talks to a `transport` —
`request(method, url, body) -> dict` — so unit tests drive the full
provider/reconciler path against a fake API surface, and production
supplies `GCEMetadataTransport` (OAuth token from the metadata server).

Host join flow (the reference's SSH command_runner equivalent, without
SSH): each TPU VM's cloud-init startup script starts a raylet pointed at
the head GCS with an `autoscaler_instance` label naming its queued
resource. The autoscaler matches registered raylets back to provider
instances by that label, so booting capacity is attributed to the
instance that launched it and slices retire atomically.
"""

from __future__ import annotations

import json
import logging
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (
    Instance,
    NodeProvider,
    NodeType,
)

logger = logging.getLogger(__name__)

INSTANCE_LABEL = "autoscaler_instance"

#: queued-resource states that still hold (or will hold) capacity
_LIVE_STATES = ("ACCEPTED", "PROVISIONING", "CREATING", "ACTIVE",
                "WAITING_FOR_RESOURCES")


def bootstrap_script(gcs_addr: str, instance_id: str) -> str:
    """Per-host startup script: join the cluster as a raylet labeled with
    the owning queued resource (reference `_private/command_runner.py`'s
    job, delivered via cloud-init instead of SSH). TPU chips are
    auto-detected on the VM (accelerators.py), so only the address and
    the instance label travel in."""
    labels = json.dumps({INSTANCE_LABEL: instance_id})
    return (
        "#!/bin/bash\n"
        "# ray_tpu TPU-VM bootstrap (generated)\n"
        f"python -m ray_tpu.scripts.cli start --address {gcs_addr} "
        f"--labels '{labels}'\n"
    )


class GCEMetadataTransport:
    """Production transport: bearer token from the GCE metadata server,
    cached until near expiry (tokens live ~1h; the reconcile loop runs
    every ~2s). Untestable in this environment (zero egress) — the
    provider logic is covered through the injected fake transport
    instead."""

    _TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                  "instance/service-accounts/default/token")

    def __init__(self):
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    def _get_token(self) -> str:
        import time
        import urllib.request

        if self._token is not None and \
                time.monotonic() < self._token_expiry:
            return self._token
        tok_req = urllib.request.Request(
            self._TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(tok_req, timeout=10) as r:
            payload = json.loads(r.read())
        self._token = payload["access_token"]
        # refresh 60s early
        self._token_expiry = time.monotonic() + \
            max(0, int(payload.get("expires_in", 0)) - 60)
        return self._token

    def request(self, method: str, url: str,
                body: Optional[dict] = None) -> dict:
        import urllib.request

        token = self._get_token()
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Authorization": f"Bearer {token}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            payload = r.read()
        return json.loads(payload) if payload else {}


class TPUQueuedResourceProvider(NodeProvider):
    """Slice instances through `projects.locations.queuedResources`.

    `node_type.slice_type` is the accelerator type string (e.g.
    "v5litepod-16" / "v5e-16"); one `create_node` equals one queued
    resource equals one whole slice. Instances report empty `node_ids` —
    raylets are matched by the INSTANCE_LABEL they register with (the
    autoscaler's label-resolution path).
    """

    _API = "https://tpu.googleapis.com/v2"

    def __init__(self, project: str, zone: str, gcs_addr: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 transport=None, name_prefix: str = "raytpu"):
        self.project = project
        self.zone = zone
        self.gcs_addr = gcs_addr
        self.runtime_version = runtime_version
        self.transport = transport or GCEMetadataTransport()
        self.name_prefix = name_prefix
        self._counter = 0
        #: queued-resource name -> node type name (the API echoes labels
        #: back, so a restarted autoscaler recovers this mapping)
        self._types: Dict[str, str] = {}

    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    # -- NodeProvider ----------------------------------------------------

    def create_node(self, node_type: NodeType) -> Instance:
        if not node_type.slice_type:
            raise ValueError(
                "TPUQueuedResourceProvider launches pod slices only; "
                f"node type {node_type.name!r} has no slice_type")
        self._counter += 1
        # random suffix: a restarted provider's counter restarts at 1,
        # and reusing a live queuedResourceId is a 409 that would wedge
        # scale-up permanently
        import os as _os
        qr_id = (f"{self.name_prefix}-{node_type.name}-{self._counter}"
                 f"-{_os.urandom(2).hex()}")
        body = {
            "tpu": {"nodeSpec": [{
                "parent": self._parent(),
                "nodeId": qr_id,
                "node": {
                    "acceleratorType": node_type.slice_type,
                    "runtimeVersion": self.runtime_version,
                    "labels": {INSTANCE_LABEL: qr_id,
                               "node_type": node_type.name},
                    "metadata": {
                        "startup-script": bootstrap_script(
                            self.gcs_addr, qr_id),
                    },
                },
            }]},
            "queueingPolicy": {},
        }
        url = (f"{self._API}/{self._parent()}/queuedResources"
               f"?queuedResourceId={qr_id}")
        self.transport.request("POST", url, body)
        logger.info("queued TPU slice %s (%s)", qr_id,
                    node_type.slice_type)
        self._types[qr_id] = node_type.name
        return Instance(qr_id, node_type.name, node_ids=[])

    def terminate_node(self, instance: Instance) -> None:
        url = (f"{self._API}/{self._parent()}/queuedResources/"
               f"{instance.instance_id}?force=true")
        self.transport.request("DELETE", url, None)
        self._types.pop(instance.instance_id, None)
        logger.info("deleted TPU slice %s", instance.instance_id)

    def non_terminated_nodes(self) -> List[Instance]:
        base = f"{self._API}/{self._parent()}/queuedResources"
        qrs: List[dict] = []
        page_token = None
        while True:
            url = base + (f"?pageToken={page_token}" if page_token else "")
            reply = self.transport.request("GET", url, None)
            qrs.extend(reply.get("queuedResources", []))
            page_token = reply.get("nextPageToken")
            if not page_token:
                break
        out: List[Instance] = []
        for qr in qrs:
            state = qr.get("state", {}).get("state", "")
            if state not in _LIVE_STATES:
                continue
            name = qr["name"].rsplit("/", 1)[-1]
            ntype = self._types.get(name)
            if ntype is None:
                # recover the mapping from the echoed node labels (e.g.
                # after an autoscaler restart)
                try:
                    ntype = qr["tpu"]["nodeSpec"][0]["node"]["labels"][
                        "node_type"]
                    self._types[name] = ntype
                except (KeyError, IndexError):
                    continue  # not one of ours
            out.append(Instance(name, ntype, node_ids=[]))
        return out
