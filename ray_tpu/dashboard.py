"""Dashboard: REST API + minimal HTML overview of the cluster.

Reference: `dashboard/` (aiohttp head process with pluggable modules;
`state_aggregator.py` backing the state API, `dashboard/client/` React
SPA). Here one aiohttp app serves the same JSON surface —
/api/nodes, /api/tasks, /api/actors, /api/objects, /api/jobs,
/api/cluster_load, /api/timeline, /api/alerts — plus a self-contained
HTML page;
heavyweight SPA tooling is out of scope.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Optional

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 2em; }
 h2 { border-bottom: 1px solid #999; }
 table { border-collapse: collapse; margin-bottom: 1.5em; }
 td, th { border: 1px solid #ccc; padding: 4px 8px; text-align: left; }
</style></head>
<body>
<h1>ray_tpu</h1>
<div id="out">loading…</div>
<script>
// every GCS-sourced string is attacker-influenced (actor/task names
// come from arbitrary cluster clients) — escape before any innerHTML
function esc(v) {
  return String(v).replace(/[&<>"']/g, c => ({'&':'&amp;','<':'&lt;',
    '>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
}
async function refresh() {
  const [nodes, actors, summary, jobs, res, events, steps, reqs, tsdb] =
    await Promise.all([
    fetch('/api/nodes').then(r => r.json()),
    fetch('/api/actors').then(r => r.json()),
    fetch('/api/task_summary').then(r => r.json()),
    fetch('/api/jobs').then(r => r.json()),
    fetch('/api/cluster_resources').then(r => r.json()),
    fetch('/api/events').then(r => r.json()),
    fetch('/api/steps').then(r => r.json()),
    fetch('/api/requests').then(r => r.json()),
    fetch('/api/timeseries').then(r => r.json()),
  ]);
  let html = '<h2>Cluster</h2><table><tr><th>total</th>' +
             '<th>available</th></tr>' +
             `<tr><td>${esc(JSON.stringify(res.total))}</td>` +
             `<td>${esc(JSON.stringify(res.available))}</td></tr>` +
             '</table>';
  html += '<h2>Nodes</h2><table><tr><th>id</th><th>alive</th>' +
             '<th>resources</th><th>available</th></tr>';
  for (const n of nodes) {
    html += `<tr><td>${esc(n.NodeID.slice(0,12))}</td>` +
            `<td>${esc(n.Alive)}</td>` +
            `<td>${esc(JSON.stringify(n.Resources))}</td>` +
            `<td>${esc(JSON.stringify(n.Available))}</td></tr>`;
  }
  html += '</table><h2>Actors</h2><table><tr><th>id</th><th>name</th>' +
          '<th>class</th><th>state</th><th>restarts</th></tr>';
  for (const a of actors) {
    html += `<tr><td>${esc(a.actor_id.slice(0,12))}</td>` +
            `<td>${esc(a.name||'')}</td>` +
            `<td>${esc(a.class_name)}</td><td>${esc(a.state)}</td>` +
            `<td>${esc(a.num_restarts)}</td></tr>`;
  }
  html += '</table><h2>Tasks</h2><table><tr><th>name</th>' +
          '<th>states</th></tr>';
  for (const [name, states] of Object.entries(summary)) {
    html += `<tr><td>${esc(name)}</td>` +
            `<td>${esc(JSON.stringify(states))}</td></tr>`;
  }
  html += '</table><h2>Jobs</h2><table><tr><th>id</th>' +
          '<th>driver</th><th>state</th><th>runtime</th></tr>';
  for (const jb of jobs) {
    html += `<tr><td>${esc(jb.job_id.slice(0,12))}</td>` +
            `<td>${esc(jb.driver_addr)}</td>` +
            `<td>${jb.finished ? 'FINISHED' : 'RUNNING'}</td>` +
            `<td>${esc(jb.runtime_s ?? '?')}s</td></tr>`;
  }
  html += '</table><h2>Training steps</h2>';
  if (steps.records && steps.records.length) {
    html += '<table><tr><th>step</th><th>total ms</th>' +
            '<th>dispatch</th><th>device</th><th>data</th>' +
            '<th>coll</th><th>ckpt</th><th>MFU</th></tr>';
    for (const s of steps.records.slice(-15).reverse()) {
      const mfu = (s.mfu == null) ? '-' : s.mfu.toFixed(4);
      html += `<tr><td>${esc(s.step)}</td>` +
              `<td>${esc((s.total_ms||0).toFixed(2))}</td>` +
              `<td>${esc((s.host_dispatch_ms||0).toFixed(2))}</td>` +
              `<td>${esc((s.device_execute_ms||0).toFixed(2))}</td>` +
              `<td>${esc((s.data_wait_ms||0).toFixed(2))}</td>` +
              `<td>${esc((s.collective_ms||0).toFixed(2))}</td>` +
              `<td>${esc((s.checkpoint_ms||0).toFixed(2))}</td>` +
              `<td>${esc(mfu)}</td></tr>`;
    }
    html += '</table>';
    const attr = steps.attribution || {};
    const parts = Object.entries(attr).filter(([k, v]) => v > 0)
      .map(([k, v]) => `${esc(k)}=${(100 * v).toFixed(1)}%`);
    if (parts.length) html += `<p>time attribution: ${parts.join('  ')}</p>`;
  } else {
    html += '<p>no step records (train with the step profiler on)</p>';
  }
  html += '<h2>Serve requests</h2>';
  if (reqs.records && reqs.records.length) {
    const s = reqs.summary || {};
    html += `<p>n=${esc(s.n||0)}  total p50/p99=` +
            `${esc(s.total_ms_p50??'-')} / ${esc(s.total_ms_p99??'-')} ms` +
            `  ttft p50=${esc(s.ttft_ms_p50??'-')} ms` +
            `  tpot p50=${esc(s.tpot_ms_p50??'-')} ms</p>`;
    html += '<table><tr><th>req</th><th>deploy</th><th>job</th>' +
            '<th>total ms</th><th>queue</th><th>admit</th>' +
            '<th>prefill</th><th>decode</th><th>ttft</th><th>tpot</th>' +
            '<th>tok</th><th>outcome</th></tr>';
    for (const r of (reqs.slowest || []).slice(0, 10)) {
      const f = v => (v == null) ? '-' : Number(v).toFixed(2);
      html += `<tr><td>${esc((r.req_id||'?').slice(0,8))}</td>` +
              `<td>${esc(r.deployment||'')}</td><td>${esc(r.job||'')}</td>` +
              `<td>${f(r.total_ms)}</td><td>${f(r.queue_ms)}</td>` +
              `<td>${f(r.admission_ms)}</td><td>${f(r.prefill_ms)}</td>` +
              `<td>${f(r.decode_ms)}</td><td>${f(r.ttft_ms)}</td>` +
              `<td>${f(r.tpot_ms)}</td><td>${esc(r.tokens_out||0)}</td>` +
              `<td>${esc(r.outcome||'ok')}</td></tr>`;
    }
    html += '</table>';
  } else {
    html += '<p>no request records (serve traffic with the request ' +
            'recorder on)</p>';
  }
  html += '<h2>Time series</h2>';
  function spark(points) {
    // inline SVG polyline over the series' own min/max
    if (!points || points.length < 2) return '(gathering)';
    const vs = points.map(p => p[1]);
    const lo = Math.min(...vs), hi = Math.max(...vs);
    const w = 160, h = 24, span = (hi - lo) || 1;
    const pts = points.map((p, i) =>
      `${(i / (points.length - 1) * w).toFixed(1)},` +
      `${(h - (p[1] - lo) / span * h).toFixed(1)}`).join(' ');
    return `<svg width="${w}" height="${h}">` +
           `<polyline points="${pts}" fill="none" stroke="#36c" ` +
           `stroke-width="1.5"/></svg>`;
  }
  const sparkRows = (tsdb.series || [])
    .filter(s => !s.name.endsWith('_bucket')).slice(0, 30);
  if (sparkRows.length) {
    html += '<table><tr><th>series</th><th>source</th>' +
            '<th>latest</th><th>trend</th></tr>';
    for (const s of sparkRows) {
      const last = s.points.length ?
        s.points[s.points.length - 1][1] : '-';
      const lbl = Object.entries(s.labels || {})
        .map(([k, v]) => `${k}=${v}`).join(',');
      html += `<tr><td>${esc(s.name)}${lbl ? esc('{'+lbl+'}') : ''}</td>` +
              `<td>${esc(s.source)}</td><td>${esc(last)}</td>` +
              `<td>${spark(s.points)}</td></tr>`;
    }
    html += '</table>';
  } else {
    html += '<p>no series yet (sampler warming up)</p>';
  }
  html += '<h2>Recent events</h2><table><tr><th>time</th>' +
          '<th>severity</th><th>source</th><th>label</th>' +
          '<th>message</th></tr>';
  for (const ev of events.slice(-25).reverse()) {
    const ts = new Date(ev.ts * 1000).toLocaleTimeString();
    html += `<tr><td>${esc(ts)}</td><td>${esc(ev.severity)}</td>` +
            `<td>${esc(ev.source)}</td><td>${esc(ev.label)}</td>` +
            `<td>${esc(ev.message)}</td></tr>`;
  }
  html += '</table>';
  document.getElementById('out').innerHTML = html;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class Dashboard:
    """Serves the REST/HTML surface from the connected driver's state
    APIs; runs its aiohttp loop on a thread (same pattern as the Serve
    proxy)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._started = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._serve_guarded,
                                        daemon=True, name="dashboard")
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError(
                f"dashboard failed to start on {host}:{port}"
                + (f": {self._error!r}" if self._error else ""))

    def _serve_guarded(self):
        try:
            self._serve()
        except BaseException as e:  # noqa: BLE001 — surfaced in __init__
            self._error = e

    def ready(self):
        return {"host": self._host, "port": self._port}

    def _serve(self):
        from aiohttp import web

        import ray_tpu
        from ray_tpu.util import state as state_api
        from ray_tpu.util.timeline import timeline

        def j(fn):
            async def handler(request):
                loop = asyncio.get_event_loop()
                try:
                    data = await loop.run_in_executor(None, fn)
                except Exception as e:  # noqa: BLE001
                    return web.json_response({"error": str(e)},
                                             status=500)
                return web.json_response(data)

            return handler

        def cluster_load():
            from ray_tpu._private.worker_api import _require_state

            cw = _require_state().core_worker
            load = cw._run_sync(cw.gcs.call("get_cluster_load", {}))
            return json.loads(json.dumps(load, default=lambda o: (
                o.hex() if isinstance(o, bytes) else str(o))))

        app = web.Application()
        app.router.add_get(
            "/", lambda r: web.Response(text=_INDEX_HTML,
                                        content_type="text/html"))
        app.router.add_get("/api/nodes", j(state_api.list_nodes))
        app.router.add_get("/api/actors", j(state_api.list_actors))
        app.router.add_get("/api/tasks", j(state_api.list_tasks))
        app.router.add_get("/api/objects", j(state_api.list_objects))
        app.router.add_get("/api/task_summary",
                           j(state_api.summarize_tasks))
        app.router.add_get("/api/timeline", j(timeline))
        app.router.add_get(
            "/api/cluster_resources",
            j(lambda: {"total": ray_tpu.cluster_resources(),
                       "available": ray_tpu.available_resources()}))
        app.router.add_get("/api/cluster_load", j(cluster_load))

        def jobs_with_runtime():
            # duration computed server-side so browser clock skew can't
            # produce negative runtimes
            now = time.time()
            out = state_api.list_jobs()
            # node-local per-job shm-store accounting (this process is
            # attached to the head node's arena; remote nodes' usage
            # shows on their raylet /metrics)
            try:
                from ray_tpu._private.worker_api import _require_state

                store = _require_state().core_worker.store
            except Exception:  # noqa: BLE001 — no store in this process
                store = None
            live_weights = sum(
                float((jb.get("quotas") or {}).get("weight", 1.0) or 1.0)
                for jb in out if not jb.get("finished"))
            for jb in out:
                start = jb.get("start_time")
                end = jb["end_time"] if jb.get("finished") else now
                jb["runtime_s"] = (round(end - start, 1)
                                   if start is not None else None)
                q = jb.get("quotas") or {}
                w = float(q.get("weight", 1.0) or 1.0)
                jb["weight"] = w
                jb["fair_share"] = (round(w / live_weights, 4)
                                    if live_weights and
                                    not jb.get("finished") else 0.0)
                st = None
                if store is not None:
                    try:
                        st = store.job_stats(bytes.fromhex(jb["job_id"]))
                    except Exception:  # noqa: BLE001 — store detached
                        st = None
                jb["object_store"] = st
            return out

        app.router.add_get("/api/jobs", j(jobs_with_runtime))
        app.router.add_get("/api/events",
                           j(lambda: state_api.list_cluster_events()[-200:]))

        def steps_panel():
            # flight-recorder plane: merged cross-process step shards
            # (when tracing is on), else this process's in-memory ring
            from ray_tpu.util import step_profiler

            records = step_profiler.collect()
            if not records:
                records = step_profiler.recent()
            records = records[-100:]
            return {"records": records,
                    "attribution": step_profiler.attribution(records),
                    "summary": step_profiler.summary()}

        app.router.add_get("/api/steps", j(steps_panel))

        def serve_llm_panel():
            # inference plane: per-replica queue depth + KV-page
            # occupancy for every serve.llm deployment (empty when no
            # serve controller is running)
            try:
                ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
                deployments = ray_tpu.get(
                    ctrl.list_deployments.remote(), timeout=10)
            except Exception:  # noqa: BLE001 — serve not started
                return {"deployments": []}
            out = []
            for name in deployments:
                try:
                    info = ray_tpu.get(
                        ctrl.get_replicas.remote(name), timeout=10)
                    rows = [ray_tpu.get(r.get_metrics.remote(),
                                        timeout=10)
                            for r in info["replicas"]]
                except Exception:  # noqa: BLE001 — replica churn
                    continue
                rows = [r for r in rows if "kv_pages_total" in r]
                if rows:
                    entry = {"deployment": name, "replicas": rows}
                    # perf rollups across replicas: prefix-cache hit
                    # rate and mean speculative accept length (absent
                    # unless the engines run with those knobs on)
                    hit_rates = [r["prefix_cache_hit_rate"]
                                 for r in rows
                                 if "prefix_cache_hit_rate" in r]
                    if hit_rates:
                        entry["prefix_cache_hit_rate"] = (
                            sum(hit_rates) / len(hit_rates))
                    accepts = [r["spec_mean_accept"] for r in rows
                               if "spec_mean_accept" in r]
                    if accepts:
                        entry["spec_mean_accept"] = (
                            sum(accepts) / len(accepts))
                    out.append(entry)
            return {"deployments": out}

        app.router.add_get("/api/serve_llm", j(serve_llm_panel))

        # metrics time-series plane: a Sampler owned by the dashboard
        # snapshots the local registry + every reachable daemon's
        # metrics_text on a cadence; /api/timeseries powers the
        # sparkline panels
        from ray_tpu.util import request_recorder
        from ray_tpu.util import tsdb as tsdb_mod

        sampler = tsdb_mod.Sampler().start()
        app.router.add_get("/api/timeseries",
                           j(lambda: sampler.db.snapshot()))

        # SLO alert plane: the evaluator rides the sampler's scrape
        # tick (Monarch-style pull evaluation — rules never touch a
        # request path); /api/alerts serves its live snapshot
        from ray_tpu.util import slo as slo_mod

        evaluator = slo_mod.AlertEvaluator(sampler.db).attach(sampler)
        app.router.add_get("/api/alerts", j(evaluator.snapshot))

        def requests_panel():
            # request-path flight recorder: merged cross-process shards
            # (when tracing is on), else this process's in-memory ring
            records = request_recorder.collect()
            if records:
                records = request_recorder.merge_by_request(records)
            else:
                records = [r.as_dict()
                           for r in request_recorder.ring().recent()]
            records = records[-100:]
            return {"records": records,
                    "summary": request_recorder.summary(records),
                    "slowest": request_recorder.slowest(records, 10)}

        app.router.add_get("/api/requests", j(requests_panel))

        def recovery_panel():
            # ownership/recovery plane: this driver's ref-table and
            # reconstruction counters (empty when not connected)
            from ray_tpu._private.object_ref import get_core_worker

            cw = get_core_worker()
            if cw is None or cw.memory_store is None:
                return {"connected": False}
            with cw._ref_lock:
                return {
                    "connected": True,
                    "owned_refs": len(cw._local_refs),
                    "borrowed_refs": len(cw._borrowed_refs),
                    "task_arg_refs": len(cw._task_arg_refs),
                    "borrower_edges": sum(
                        len(v) for v in cw._borrowers.values()),
                    "lineage_bytes": cw._lineage_bytes,
                    "lineage_tasks": len(cw._lineage),
                    "lineage_evictions": cw._stats_lineage_evictions,
                    "reconstructions": cw._stats_reconstructions,
                    "reconstruction_failures":
                        cw._stats_reconstruction_failures,
                    "reconstruction_depth_max":
                        cw._stats_reconstruction_depth_max,
                    "objects_freed": cw._stats_objects_freed,
                }

        app.router.add_get("/api/recovery", j(recovery_panel))

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self._host, self._port)
        loop.run_until_complete(site.start())
        self._started.set()
        loop.run_forever()


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    """Start the dashboard in this (driver) process. For a long-lived
    cluster service, run `python -m ray_tpu dashboard --address ...` on
    any machine that can reach the GCS."""
    return Dashboard(host, port)
