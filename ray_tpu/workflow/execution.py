"""Workflow executor: checkpointed step-by-step DAG execution.

Reference: `python/ray/workflow/workflow_executor.py:32` (the in-flight
execution state machine) + `workflow_storage.py` (step-result storage).
Steps are content-keyed by their position in the DAG; a completed step's
pickled result short-circuits re-execution on resume.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.dag import DAGNode, InputNode

_storage_root = os.path.expanduser("~/.ray_tpu_workflows")

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    if storage:
        _storage_root = storage
    os.makedirs(_storage_root, exist_ok=True)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root, workflow_id)


def _write(path: str, obj: Any) -> None:
    # cloudpickle: step functions are often closures/lambdas the stdlib
    # pickler cannot serialize
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.dumps(obj))
    os.replace(tmp, path)


def _read(path: str) -> Any:
    with open(path, "rb") as f:
        return serialization.loads(f.read())


def _step_key(node: DAGNode, dag_path: str) -> str:
    """Stable identity for a step: its position in the DAG plus the
    function name (the DAG shape is fixed across resumes)."""
    name = getattr(node._fn, "__name__", "step")
    return hashlib.sha1(f"{dag_path}:{name}".encode()).hexdigest()[:16]


def _execute_node(node: Any, wf_dir: str, dag_path: str,
                  root_args: tuple,
                  run_cache: Optional[Dict[int, Any]] = None) -> Any:
    """Post-order execution with per-step checkpoints. Returns the
    step's VALUE (not a ref) — each step is a barrier, which is what
    makes the checkpoint a consistent resume point. `run_cache` dedupes
    shared (diamond) nodes within one run: a node reached via two paths
    must execute once, like dag.execute's per-run cache."""
    if isinstance(node, InputNode):
        return node.pick(root_args)
    if not isinstance(node, DAGNode):
        return node
    if run_cache is None:
        run_cache = {}
    if id(node) in run_cache:
        return run_cache[id(node)]
    key = _step_key(node, dag_path)
    ckpt = os.path.join(wf_dir, f"step-{key}.pkl")
    if os.path.exists(ckpt):
        value = _read(ckpt)
        run_cache[id(node)] = value
        return value
    args = [
        _execute_node(a, wf_dir, f"{dag_path}/{i}", root_args, run_cache)
        for i, a in enumerate(node._args)
    ]
    kwargs = {
        k: _execute_node(v, wf_dir, f"{dag_path}/{k}", root_args,
                         run_cache)
        for k, v in node._kwargs.items()
    }
    value = ray_tpu.get(node._fn.remote(*args, **kwargs))
    _write(ckpt, value)
    run_cache[id(node)] = value
    return value


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None) -> Any:
    """Execute to completion, checkpointing each step; returns the final
    value. A re-run (or `resume`) with the same workflow_id skips
    completed steps."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    meta_path = os.path.join(wf_dir, "meta.pkl")
    _write(meta_path, {"workflow_id": workflow_id, "status": RUNNING,
                       "dag": dag, "args": args, "ts": time.time()})
    try:
        out = _execute_node(dag, wf_dir, "", args)
    except BaseException:
        meta = _read(meta_path)
        meta["status"] = FAILED
        _write(meta_path, meta)
        raise
    meta = _read(meta_path)
    meta.update(status=SUCCEEDED, result=out)
    _write(meta_path, meta)
    return out


def run_async(dag: DAGNode, *args,
              workflow_id: Optional[str] = None):
    """Run in a detached driver thread; returns the workflow id."""
    import threading

    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    threading.Thread(
        target=lambda: _swallow(run, dag, *args,
                                workflow_id=workflow_id),
        daemon=True).start()
    return workflow_id


def _swallow(fn, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except BaseException:  # noqa: BLE001 — recorded in meta
        pass


def resume(workflow_id: str) -> Any:
    """Re-run a failed/interrupted workflow: completed steps come from
    their checkpoints, only the rest re-execute (reference:
    `workflow.resume`)."""
    meta = _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))
    return run(meta["dag"], *meta["args"], workflow_id=workflow_id)


def status(workflow_id: str) -> str:
    return _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))["status"]


def get_output(workflow_id: str) -> Any:
    meta = _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))
    if meta["status"] != SUCCEEDED:
        raise RuntimeError(f"workflow {workflow_id} is {meta['status']}")
    return meta["result"]


def list_all() -> List[Dict[str, Any]]:
    out = []
    if not os.path.isdir(_storage_root):
        return out
    for wid in os.listdir(_storage_root):
        meta_path = os.path.join(_storage_root, wid, "meta.pkl")
        if os.path.exists(meta_path):
            meta = _read(meta_path)
            out.append({"workflow_id": wid, "status": meta["status"]})
    return out
