"""Workflow executor: checkpointed step-by-step DAG execution.

Reference: `python/ray/workflow/workflow_executor.py:32` (the in-flight
execution state machine) + `workflow_storage.py` (step-result storage).
Steps are content-keyed by their position in the DAG; a completed step's
pickled result short-circuits re-execution on resume.

Dynamic workflows (VERDICT r4 item 10; reference: `workflow.continuation`
and the dynamic-DAG growth in `workflow_executor.py`): a step may RETURN
`workflow.continuation(sub_dag)` — the executor checkpoints the returned
sub-DAG under the parent step's key, then executes it in a nested step
namespace. Recovery crosses the boundary: a crash mid-continuation
resumes INTO the continuation (rebuilt from the parent's checkpoint)
without re-running the parent, and completed continuation steps skip via
their own checkpoints. Chained continuations (a continuation returning
another continuation) unwind iteratively, so recursion depth is bounded
by the continuation chain, not the Python stack.

Durable events (reference `workflow.wait_for_event` /
`python/ray/workflow/event_listener.py`): `wait_for_event(name)` is a
step that blocks until `send_event(workflow_id, name, payload)` lands;
the received payload checkpoints like any step result, so a resumed
workflow does not re-wait a consumed event.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import serialization
from ray_tpu.dag import DAGNode, InputNode

_storage_root = os.path.expanduser("~/.ray_tpu_workflows")

RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"


class Continuation:
    """A step's request to continue INTO a dynamically-built sub-DAG
    (reference `workflow.continuation`): the workflow's final value for
    that step becomes the sub-DAG's result."""

    def __init__(self, node: DAGNode):
        if not isinstance(node, DAGNode):
            raise TypeError("continuation() takes a bound DAG node")
        self.node = node


def continuation(node: DAGNode) -> Continuation:
    return Continuation(node)


class EventStep:
    """Durable external-event wait (reference `workflow.wait_for_event`):
    blocks the workflow until `send_event(workflow_id, name, payload)`;
    the payload checkpoints as the step's value."""

    def __init__(self, name: str):
        self.name = name


def wait_for_event(name: str) -> EventStep:
    return EventStep(name)


def send_event(workflow_id: str, name: str, payload: Any = None) -> None:
    """Deliver an event to a (possibly running) workflow. Durable: the
    payload is written before the waiting step can observe it."""
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    _write(os.path.join(wf_dir, f"event-{name}.pkl"), payload)


def init(storage: Optional[str] = None) -> None:
    global _storage_root
    if storage:
        _storage_root = storage
    os.makedirs(_storage_root, exist_ok=True)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root, workflow_id)


def _write(path: str, obj: Any) -> None:
    # cloudpickle: step functions are often closures/lambdas the stdlib
    # pickler cannot serialize
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(serialization.dumps(obj))
    os.replace(tmp, path)


def _read(path: str) -> Any:
    with open(path, "rb") as f:
        return serialization.loads(f.read())


def _step_key(node: DAGNode, dag_path: str) -> str:
    """Stable identity for a step: its position in the DAG plus the
    function name (the DAG shape is fixed across resumes)."""
    name = getattr(node._fn, "__name__", "step")
    return hashlib.sha1(f"{dag_path}:{name}".encode()).hexdigest()[:16]


def _execute_node(node: Any, wf_dir: str, dag_path: str,
                  root_args: tuple,
                  run_cache: Optional[Dict[int, Any]] = None) -> Any:
    """Post-order execution with per-step checkpoints. Returns the
    step's VALUE (not a ref) — each step is a barrier, which is what
    makes the checkpoint a consistent resume point. `run_cache` dedupes
    shared (diamond) nodes within one run: a node reached via two paths
    must execute once, like dag.execute's per-run cache."""
    if isinstance(node, InputNode):
        return node.pick(root_args)
    if isinstance(node, EventStep):
        return _execute_event(node, wf_dir, dag_path)
    if not isinstance(node, DAGNode):
        return node
    if run_cache is None:
        run_cache = {}
    if id(node) in run_cache:
        return run_cache[id(node)]
    # Unwind continuations ITERATIVELY in THIS frame: each hop runs one
    # step (whose args recurse over the static DAG only) and may yield
    # the next hop's sub-DAG. A continuation chain of any length costs
    # zero extra stack — hop k's namespace is dag_path + "@c0"*k, stable
    # across resumes because the chain is rebuilt from checkpoints.
    ckpt = os.path.join(wf_dir, f"step-{_step_key(node, dag_path)}.pkl")
    cur_node, cur_path = node, dag_path
    value = _execute_step(cur_node, wf_dir, cur_path, root_args,
                          run_cache)
    had_continuation = isinstance(value, Continuation)
    while isinstance(value, Continuation):
        cur_node, cur_path = value.node, cur_path + "@c0"
        value = _execute_step(cur_node, wf_dir, cur_path, root_args,
                              run_cache)
    if had_continuation:
        _write(ckpt, value)  # collapse the record to the final value
    run_cache[id(node)] = value
    return value


def _execute_step(node: DAGNode, wf_dir: str, dag_path: str,
                  root_args: tuple, run_cache: Dict[int, Any]) -> Any:
    """Run ONE step (args resolved recursively over the static DAG) and
    return its raw value — possibly a Continuation, which the CALLER
    unwinds. The checkpoint is written before returning, so a crash
    inside a continuation resumes into it without re-running this
    step."""
    key = _step_key(node, dag_path)
    ckpt = os.path.join(wf_dir, f"step-{key}.pkl")
    if os.path.exists(ckpt):
        return _read(ckpt)
    args = [
        _execute_node(a, wf_dir, f"{dag_path}/{i}", root_args, run_cache)
        for i, a in enumerate(node._args)
    ]
    kwargs = {
        k: _execute_node(v, wf_dir, f"{dag_path}/{k}", root_args,
                         run_cache)
        for k, v in node._kwargs.items()
    }
    value = ray_tpu.get(node._fn.remote(*args, **kwargs))
    _write(ckpt, value)
    return value


def _execute_event(node: EventStep, wf_dir: str, dag_path: str) -> Any:
    key = hashlib.sha1(f"{dag_path}:event:{node.name}".encode()) \
        .hexdigest()[:16]
    ckpt = os.path.join(wf_dir, f"step-{key}.pkl")
    if os.path.exists(ckpt):
        return _read(ckpt)  # event already consumed pre-crash
    path = os.path.join(wf_dir, f"event-{node.name}.pkl")
    while not os.path.exists(path):
        time.sleep(0.05)
    payload = _read(path)
    _write(ckpt, payload)
    return payload


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None,
        metadata: Optional[Dict[str, Any]] = None) -> Any:
    """Execute to completion, checkpointing each step; returns the final
    value. A re-run (or `resume`) with the same workflow_id skips
    completed steps. `metadata` attaches user key/values retrievable via
    `get_metadata` (reference `workflow.run(metadata=...)`)."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    meta_path = os.path.join(wf_dir, "meta.pkl")
    prior = (_read(meta_path) if os.path.exists(meta_path) else {})
    _write(meta_path, {"workflow_id": workflow_id, "status": RUNNING,
                       "dag": dag, "args": args, "ts": time.time(),
                       "start_time": prior.get("start_time",
                                               time.time()),
                       "user_metadata": (metadata if metadata is not None
                                         else prior.get("user_metadata",
                                                        {}))})
    try:
        out = _execute_node(dag, wf_dir, "", args)
    except BaseException:
        meta = _read(meta_path)
        meta["status"] = FAILED
        meta["end_time"] = time.time()
        _write(meta_path, meta)
        raise
    meta = _read(meta_path)
    meta.update(status=SUCCEEDED, result=out, end_time=time.time())
    _write(meta_path, meta)
    return out


def run_async(dag: DAGNode, *args,
              workflow_id: Optional[str] = None,
              metadata: Optional[Dict[str, Any]] = None):
    """Run in a detached driver thread; returns the workflow id."""
    import threading

    workflow_id = workflow_id or f"wf-{int(time.time() * 1000)}"
    # write the meta record BEFORE returning so status() is immediately
    # answerable (the thread re-writes it as RUNNING on entry)
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    _write(os.path.join(wf_dir, "meta.pkl"),
           {"workflow_id": workflow_id, "status": RUNNING, "dag": dag,
            "args": args, "ts": time.time(), "start_time": time.time(),
            "user_metadata": metadata or {}})
    threading.Thread(
        target=lambda: _swallow(run, dag, *args,
                                workflow_id=workflow_id,
                                metadata=metadata),
        daemon=True).start()
    return workflow_id


def _swallow(fn, *args, **kwargs):
    try:
        fn(*args, **kwargs)
    except BaseException:  # noqa: BLE001 — recorded in meta
        pass


def resume(workflow_id: str) -> Any:
    """Re-run a failed/interrupted workflow: completed steps come from
    their checkpoints, only the rest re-execute (reference:
    `workflow.resume`)."""
    meta = _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))
    return run(meta["dag"], *meta["args"], workflow_id=workflow_id)


def status(workflow_id: str) -> str:
    return _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))["status"]


def get_metadata(workflow_id: str) -> Dict[str, Any]:
    """Workflow metadata (reference `workflow.get_metadata`): status,
    timing, user metadata, and the completed-step checkpoint count."""
    wf_dir = _wf_dir(workflow_id)
    meta = _read(os.path.join(wf_dir, "meta.pkl"))
    steps = [f for f in os.listdir(wf_dir) if f.startswith("step-")]
    return {
        "workflow_id": workflow_id,
        "status": meta["status"],
        "start_time": meta.get("start_time"),
        "end_time": meta.get("end_time"),
        "user_metadata": dict(meta.get("user_metadata", {})),
        "steps_checkpointed": len(steps),
    }


def get_output(workflow_id: str) -> Any:
    meta = _read(os.path.join(_wf_dir(workflow_id), "meta.pkl"))
    if meta["status"] != SUCCEEDED:
        raise RuntimeError(f"workflow {workflow_id} is {meta['status']}")
    return meta["result"]


def list_all() -> List[Dict[str, Any]]:
    out = []
    if not os.path.isdir(_storage_root):
        return out
    for wid in os.listdir(_storage_root):
        meta_path = os.path.join(_storage_root, wid, "meta.pkl")
        if os.path.exists(meta_path):
            meta = _read(meta_path)
            out.append({"workflow_id": wid, "status": meta["status"]})
    return out
