"""Durable workflows: DAG execution with step-level checkpoints + resume.

Reference: `python/ray/workflow/` — `workflow.run` executes a DAG of
steps with each step's output checkpointed to storage
(`workflow_executor.py:32`, `workflow_storage.py`), so a crashed
workflow resumes from the last completed step rather than restarting.

Surface here: `workflow.run(dag_node, workflow_id=..., metadata=...)`
over `ray_tpu.dag` DAGs, `workflow.resume(workflow_id)`,
`workflow.status`, `workflow.get_metadata`, `workflow.list_all`;
dynamic workflows via `workflow.continuation(sub_dag)` (a step's return
value grows the DAG, with recovery across the continuation boundary);
durable external events via `workflow.wait_for_event(name)` +
`workflow.send_event(workflow_id, name, payload)`. Storage is a
filesystem directory (set via `workflow.init(storage=...)`).
"""

from ray_tpu.workflow.execution import (
    continuation,
    get_metadata,
    get_output,
    init,
    list_all,
    resume,
    run,
    run_async,
    send_event,
    status,
    wait_for_event,
)

__all__ = ["init", "run", "run_async", "resume", "status", "list_all",
           "continuation", "wait_for_event", "send_event",
           "get_metadata", "get_output"]
