"""Durable workflows: DAG execution with step-level checkpoints + resume.

Reference: `python/ray/workflow/` — `workflow.run` executes a DAG of
steps with each step's output checkpointed to storage
(`workflow_executor.py:32`, `workflow_storage.py`), so a crashed
workflow resumes from the last completed step rather than restarting.

Surface here: `workflow.run(dag_node, workflow_id=...)` over
`ray_tpu.dag` DAGs, `workflow.resume(workflow_id)`, `workflow.status`,
`workflow.list_all`. Storage is a filesystem directory (set via
`workflow.init(storage=...)`).
"""

from ray_tpu.workflow.execution import (
    init,
    list_all,
    resume,
    run,
    run_async,
    status,
)

__all__ = ["init", "run", "run_async", "resume", "status", "list_all"]
