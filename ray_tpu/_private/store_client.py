"""Pluggable GCS table storage (reference: `StoreClient`
`src/ray/gcs/store_client/store_client.h` with its InMemory and Redis
implementations, `{in_memory,redis}_store_client.h`).

The GCS keeps its working set in process memory; a StoreClient is the
DURABILITY backend written through at every table mutation — unlike the
periodic snapshot, a mutation is on disk before anything observes its
effects, so a GCS killed at any instant restarts with current tables.

`FileStoreClient` plays the Redis role with zero dependencies: one
directory per table, one file per key, atomic-rename writes. The
interface is the seam where an actual Redis/etcd client would slot in
(zero-egress environments get the file backend).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Any, Dict, Optional
from urllib.parse import quote, unquote

logger = logging.getLogger(__name__)


class StoreClient:
    """Key/value-per-table durability backend."""

    def put(self, table: str, key: bytes, value: Any) -> None:
        self.put_blob(table, key, pickle.dumps(value))

    def put_blob(self, table: str, key: bytes, blob: bytes) -> None:
        """Store an already-pickled value (the GCS serializes on its
        event loop for a consistent view, then hands the blob to a
        writer thread)."""
        raise NotImplementedError

    def delete(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def get_all(self, table: str) -> Dict[bytes, Any]:
        raise NotImplementedError

    def tables(self) -> list:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """No durability — the default when no store is configured (kept for
    interface parity with the reference's InMemoryStoreClient)."""

    def __init__(self):
        self._tables: Dict[str, Dict[bytes, Any]] = {}

    def put_blob(self, table, key, blob):
        self._tables.setdefault(table, {})[key] = pickle.loads(blob)

    def delete(self, table, key):
        self._tables.get(table, {}).pop(key, None)

    def get_all(self, table):
        return dict(self._tables.get(table, {}))

    def tables(self):
        return list(self._tables)


class FileStoreClient(StoreClient):
    """File-per-key store: `root/<table>/<key hex>` holding the pickled
    value. Writes go through a temp file + `os.replace`, so a reader (or
    a restarting GCS) never sees a torn record."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # Migrate table dirs written by the pre-quote encoding (which
        # left ':' etc. intact): without this, a store created before
        # the reversible encoding restores every kv namespace empty —
        # 'kv:default' would be read back but fetched as 'kv%3Adefault'.
        for name in os.listdir(root):
            canon = quote(unquote(name), safe="")
            if canon != name:
                src = os.path.join(root, name)
                dst = os.path.join(root, canon)
                if not os.path.isdir(src):
                    continue
                if not os.path.exists(dst):
                    os.replace(src, dst)
                    continue
                # Mixed-version writes left BOTH dirs: merge the legacy
                # dir's key files into the canonical one (existing keys
                # win — they were written by the newer GCS) instead of
                # silently orphaning the legacy keys on restore.
                merged = 0
                for key_name in os.listdir(src):
                    path = os.path.join(src, key_name)
                    target = os.path.join(dst, key_name)
                    if (".tmp." in key_name or os.path.exists(target)):
                        # torn leftover, or superseded by a newer write
                        # in the canonical dir — either way dead data;
                        # removing it lets the legacy dir go away (a
                        # lingering dir would double-list the table)
                        os.unlink(path)
                        continue
                    os.replace(path, target)
                    merged += 1
                logger.warning(
                    "FileStoreClient: merged %d legacy key file(s) from "
                    "%r into %r (keys already present in the canonical "
                    "dir were kept)", merged, name, canon)
                try:
                    os.rmdir(src)
                except OSError:
                    pass

    def _table_dir(self, table: str) -> str:
        # Reversible path-safe encoding: tables() reconstructs kv
        # namespaces from directory names after a GCS restart, so the
        # mapping must be injective ('a/b' and 'a_b' must not collide,
        # and a namespace containing '/' must round-trip exactly).
        return os.path.join(self.root, quote(table, safe=""))

    def put_blob(self, table, key, blob):
        d = self._table_dir(table)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, key.hex())
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def delete(self, table, key):
        try:
            os.unlink(os.path.join(self._table_dir(table), key.hex()))
        except FileNotFoundError:
            pass

    def get_all(self, table):
        d = self._table_dir(table)
        out: Dict[bytes, Any] = {}
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return out
        for name in names:
            if ".tmp." in name:
                continue
            try:
                with open(os.path.join(d, name), "rb") as f:
                    out[bytes.fromhex(name)] = pickle.load(f)
            except (OSError, ValueError, pickle.PickleError):
                continue  # torn leftover; atomic writes make this rare
        return out

    def tables(self):
        try:
            return [unquote(n) for n in os.listdir(self.root)
                    if os.path.isdir(os.path.join(self.root, n))]
        except FileNotFoundError:
            return []


def make_store_client(path: Optional[str]) -> Optional[StoreClient]:
    """Factory for the GCS: a path selects the file backend; None means
    no external store (snapshot-only persistence, if configured)."""
    return FileStoreClient(path) if path else None
