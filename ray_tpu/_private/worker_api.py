"""Driver-side runtime: init/shutdown/remote/get/put/wait + actor frontends.

Reference: `python/ray/_private/worker.py` (init/connect/get/put/wait),
`python/ray/remote_function.py` (RemoteFunction), `python/ray/actor.py`
(ActorClass/ActorHandle/ActorMethod).
"""

from __future__ import annotations

import atexit
import dataclasses
import functools
import inspect
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu._private import task as task_mod
from ray_tpu._private.config import Config, global_config
from ray_tpu._private.core_worker import (
    ActorDiedError,
    CoreWorker,
    GetTimeoutError,
    RayTaskError,
    TaskCancelledError,
)
from ray_tpu._private.ids import ActorID, JobID, ObjectID, PlacementGroupID
from ray_tpu._private.node import Cluster
from ray_tpu._private.object_ref import ObjectRef, get_core_worker
from ray_tpu._private.object_store import ObjectStore

_global_lock = threading.Lock()
_global_state: Optional["GlobalState"] = None
# env keys exported for _system_config (cleared on shutdown so one
# test's overrides never leak into the next cluster)
_exported_config_env: list = []


class GlobalState:
    def __init__(self, cluster: Cluster | None, core_worker: CoreWorker,
                 owns_cluster: bool, client=None):
        self.cluster = cluster
        self.core_worker = core_worker
        self.owns_cluster = owns_cluster
        # Ray-Client mode: a ClientContext proxying every call to a
        # cluster-side ClientServer (reference: python/ray/util/client)
        self.client = client


def is_initialized() -> bool:
    return _global_state is not None


def _require_state() -> GlobalState:
    # Inside a worker process there is a process-global CoreWorker but no
    # GlobalState; fall back to it so tasks can call the public API.
    if _global_state is None:
        cw = get_core_worker()
        if cw is not None:
            return GlobalState(None, cw, owns_cluster=False)
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_state


def init(
    address: str | None = None,
    num_cpus: int | None = None,
    num_tpus: int | None = None,
    resources: Dict[str, float] | None = None,
    object_store_memory: int | None = None,
    runtime_env: dict | None = None,
    job_quotas: dict | None = None,
    _system_config: dict | None = None,
    ignore_reinit_error: bool = False,
):
    """Start (or connect to) a ray_tpu cluster and attach this driver.

    ``job_quotas`` registers this driver's job with the multi-tenant
    isolation plane: ``{"weight": 2.0, "cpu": 8.0, "memory": 2**30,
    "object_store_bytes": 256 * 2**20}`` — weight sets the job's share
    of contended dispatch; the quota fields (0/absent = unlimited) cap
    concurrently held CPU/memory and shm-store bytes (see README
    "Multi-tenancy")."""
    global _global_state
    with _global_lock:
        if _global_state is not None:
            if ignore_reinit_error:
                return _global_state
            raise RuntimeError("ray_tpu.init() already called")
        # copy — mutating the cached global would leak overrides into
        # the next init() in this process after shutdown cleans the env
        cfg = dataclasses.replace(global_config())
        if _system_config:
            cfg.update(_system_config)
            # daemons (GCS/raylet/workers) are subprocesses reading
            # Config.from_env() — export the overrides so the whole
            # cluster, not just this driver, sees them
            from ray_tpu._private.config import _ENV_PREFIX
            global _exported_config_env
            for k, v in _system_config.items():
                key = _ENV_PREFIX + k.upper()
                # always export: an explicit _system_config override beats
                # a pre-existing shell var (which the driver's own Config
                # already ignored via cfg.update) — otherwise driver and
                # daemons would run with different values. The prior value
                # is restored on shutdown.
                _exported_config_env.append((key, os.environ.get(key)))
                os.environ[key] = str(v)

        if address is None:
            # CLI-submitted drivers find their cluster through the env
            # (reference: RAY_ADDRESS)
            address = os.environ.get("RAY_TPU_ADDRESS") or None
        if address and (address.startswith("client://")
                        or address.startswith("ray://")):
            # thin remote driver: no local daemons, everything proxied.
            # Local-cluster knobs make no sense here — fail loudly
            # rather than silently ignoring them.
            unsupported = {
                "num_cpus": num_cpus, "num_tpus": num_tpus,
                "resources": resources,
                "object_store_memory": object_store_memory,
                "runtime_env": runtime_env,
                "_system_config": _system_config,
            }
            bad = [k for k, v in unsupported.items() if v is not None]
            if bad:
                raise ValueError(
                    f"init(address='client://...') does not accept "
                    f"{bad} — configure the cluster where the "
                    f"client-server runs")
            from ray_tpu.util.client import ClientContext

            host_port = address.split("://", 1)[1]
            ctx = ClientContext(host_port)
            _global_state = GlobalState(None, None, owns_cluster=False,
                                        client=ctx)
            atexit.register(shutdown)
            return _global_state
        if address is None:
            node_resources = dict(resources or {})
            import os as _os
            node_resources.setdefault("CPU", float(num_cpus if num_cpus is not None
                                                   else (_os.cpu_count() or 1)))
            if num_tpus is not None:
                node_resources["TPU"] = float(num_tpus)
            else:
                node_resources.setdefault("TPU", float(_detect_tpu_chips()))
            cluster = Cluster(
                head_resources=node_resources,
                object_store_memory=object_store_memory,
            )
            owns = True
            gcs_addr = cluster.gcs_addr
            head = cluster.head_node
            raylet_addr = head.raylet_addr
            store_name = head.store_name
            node_id_hex = head.node_id_hex
        else:
            cluster = None
            owns = False
            gcs_addr = address
            raylet_addr, store_name, node_id_hex = \
                _discover_local_raylet(address)

        job_id = JobID.from_random()
        store = ObjectStore.attach(store_name)
        cw = CoreWorker(
            mode="driver",
            gcs_addr=gcs_addr,
            raylet_addr=raylet_addr,
            job_id=job_id,
            store=store,
            node_id_hex=node_id_hex,
            config=cfg,
        )
        cw.start()
        cw._run_sync(cw.gcs.call("register_job", {
            "job_id": job_id.binary(),
            "driver_addr": cw.address,
            "quotas": dict(job_quotas) if job_quotas else None,
        }))
        if job_quotas:
            # the driver-local scheduler registry too: this process's
            # raylet learns via pubsub, but client-side bits (e.g. the
            # fair queue weight default) read the local registry
            from ray_tpu._private import scheduling as _sched
            _sched.set_job_quota(job_id.binary(),
                                 _sched.JobQuota.from_dict(job_quotas))
        if runtime_env is not None:
            # job-level default applied to every task/actor without its
            # own runtime_env (reference: ray.init(runtime_env=...))
            from ray_tpu._private import runtime_env as renv_mod

            cw.job_runtime_env = renv_mod.prepare(cw, runtime_env)
        _global_state = GlobalState(cluster, cw, owns)
        atexit.register(shutdown)
        return _global_state


def _detect_tpu_chips() -> int:
    """TPU chip autodetection (reference:
    python/ray/_private/accelerators/tpu.py:104-120 — /dev/accel* and vfio)."""
    import glob
    chips = len(glob.glob("/dev/accel*"))
    if chips == 0:
        chips = len(glob.glob("/dev/vfio/*")) - (
            1 if glob.glob("/dev/vfio/vfio") else 0
        )
    return max(chips, 0)


def _discover_local_raylet(gcs_addr: str):
    import asyncio

    from ray_tpu._private.rpc import RpcClient

    async def query():
        client = await RpcClient(gcs_addr).connect()
        nodes = await client.call("get_nodes", {})
        await client.close()
        alive = [n for n in nodes if n["alive"]]
        if not alive:
            raise RuntimeError("no alive nodes in cluster")
        import os as _os
        hostname = _os.uname().nodename
        for n in alive:
            if n.get("hostname") == hostname:
                return n
        return alive[0]

    node = asyncio.run(query())
    # Ask the raylet for its store name.
    async def info(addr):
        client = await RpcClient(addr).connect()
        reply = await client.call("node_info", {})
        await client.close()
        return reply

    reply = asyncio.run(info(node["raylet_addr"]))
    return (node["raylet_addr"], reply["store_name"],
            node["node_id"].hex() if isinstance(node.get("node_id"), bytes)
            else str(node.get("node_id", "")))


def shutdown():
    global _global_state
    with _global_lock:
        state = _global_state
        if state is None:
            return
        _global_state = None
        if state.client is not None:
            state.client.disconnect()
            return
        try:
            state.core_worker._run_sync(
                state.core_worker.gcs.call(
                    "finish_job",
                    {"job_id": state.core_worker.job_id.binary()},
                ),
                timeout=5,
            )
        except Exception:
            pass
        state.core_worker.shutdown()
        if state.owns_cluster and state.cluster is not None:
            state.cluster.shutdown()
        global _exported_config_env
        for key, prior in _exported_config_env:
            if prior is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = prior
        _exported_config_env = []


def put(value: Any) -> ObjectRef:
    state = _require_state()
    if state.client is not None:
        return state.client.put(value)
    return state.core_worker.put(value)


def get(refs, timeout: float | None = None):
    state = _require_state()
    if state.client is not None:
        return state.client.get(refs, timeout=timeout)
    return state.core_worker.get(refs, timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None):
    state = _require_state()
    if state.client is not None:
        return state.client.wait(refs, num_returns=num_returns,
                                 timeout=timeout)
    return state.core_worker.wait(refs, num_returns, timeout)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    state = _require_state()
    if state.client is not None:
        state.client.kill(actor, no_restart=no_restart)
        return
    state.core_worker.kill_actor(actor._actor_id, no_restart)


def cancel(ref, *, force: bool = False, recursive: bool = True):
    """Cancel the task producing `ref` (reference `ray.cancel`,
    `python/ray/_private/worker.py:2932`): a pending task is dequeued, a
    running one is interrupted at its executor, `force=True` kills the
    executing worker process, and `recursive=True` also cancels the
    task's children. Best-effort — a task that already finished is
    unaffected. `ray_tpu.get` on a cancelled task raises
    TaskCancelledError."""
    state = _require_state()
    if state.client is not None:
        state.client.cancel(ref, force=force, recursive=recursive)
        return
    state.core_worker.cancel(ref, force=force, recursive=recursive)


# ----------------------------------------------------------------------
# @remote — tasks
# ----------------------------------------------------------------------

_OPTION_DEFAULTS = dict(
    num_cpus=None,
    num_tpus=None,
    resources=None,
    num_returns=1,
    max_retries=None,
    max_restarts=0,
    max_concurrency=1,
    concurrency_groups=None,
    name=None,
    lifetime=None,
    scheduling_strategy=None,
    placement_group=None,
    placement_group_bundle_index=-1,
    runtime_env=None,
)


def _prepared_runtime_env(holder, cw, opts):
    """Resolve + upload the runtime env once per RemoteFunction/ActorClass
    instance (content-addressed, so repeats are cheap anyway); falls back
    to the job-level default from init(runtime_env=...).

    A per-task/actor runtime_env inherits the job-level one field-wise
    (reference: `python/ray/_private/runtime_env/validation.py` — child
    fields override, `env_vars` merge key-wise), so e.g. Train workers
    that add env_vars keep the job's working_dir/pip."""
    renv = opts.get("runtime_env")
    job_env = getattr(cw, "job_runtime_env", None)
    if renv is None:
        return job_env
    cached = getattr(holder, "_prepared_env", None)
    if cached is None:
        from ray_tpu._private import runtime_env as renv_mod

        cached = renv_mod.prepare(cw, renv)
        if job_env:
            # wire-level merge: job_env's paths are already uploaded
            # (content keys), so inheritance composes prepared forms
            cached = renv_mod.merge_wire(job_env, cached)
        holder._prepared_env = cached
    return cached


def _resource_dict(opts: dict, default_cpu: float) -> Dict[str, float]:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    num_tpus = opts.get("num_tpus")
    resources["CPU"] = float(num_cpus) if num_cpus is not None else default_cpu
    if num_tpus is not None:
        resources["TPU"] = float(num_tpus)
    return resources


def _strategy_fields(opts: dict):
    strategy = task_mod.STRATEGY_DEFAULT
    node_id = None
    soft = False
    pg_id = None
    bundle_index = opts.get("placement_group_bundle_index", -1)
    ss = opts.get("scheduling_strategy")
    if isinstance(ss, str) and ss == "SPREAD":
        strategy = task_mod.STRATEGY_SPREAD
    elif isinstance(ss, NodeAffinitySchedulingStrategy):
        strategy = task_mod.STRATEGY_NODE_AFFINITY
        node_id = bytes.fromhex(ss.node_id)
        soft = ss.soft
    elif isinstance(ss, PlacementGroupSchedulingStrategy):
        strategy = task_mod.STRATEGY_PLACEMENT_GROUP
        pg_id = ss.placement_group.id.binary()
        bundle_index = ss.placement_group_bundle_index
    pg = opts.get("placement_group")
    if pg is not None:
        strategy = task_mod.STRATEGY_PLACEMENT_GROUP
        pg_id = pg.id.binary()
    return strategy, node_id, soft, pg_id, bundle_index


def _client_options(opts: dict) -> dict:
    """Options forwarded to the cluster-side ClientServer: only
    non-default values; scheduling objects are not client-serializable
    yet (reference Ray Client has the same restriction surface)."""
    out = {}
    for k, v in opts.items():
        if v == _OPTION_DEFAULTS.get(k, None):
            continue
        if k in ("scheduling_strategy", "placement_group",
                 "placement_group_bundle_index"):
            raise ValueError(
                f"option {k!r} is not supported in client mode")
        if k == "num_returns" and v == "streaming":
            raise ValueError(
                "num_returns='streaming' is not supported in client mode")
        out[k] = v
    return out


class RemoteFunction:
    def __init__(self, fn, options: dict, function_key: bytes | None = None):
        self._fn = fn
        self._options = {**_OPTION_DEFAULTS, **options}
        self._function_key = function_key
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        return RemoteFunction(self._fn, {**self._options, **opts},
                              self._function_key)

    def _ensure_pushed(self, cw: CoreWorker) -> bytes:
        # Benign race: two threads may push the same function; the GCS KV
        # dedupes on the content hash (overwrite=False).
        if self._function_key is None:
            self._function_key = cw.push_function(self._fn)
        return self._function_key

    def __reduce__(self):
        # Remote functions captured in closures of other tasks must travel;
        # the function itself is cloudpickled by value (reference pickles
        # RemoteFunction the same way).
        return (RemoteFunction, (self._fn, self._options, self._function_key))

    def remote(self, *args, **kwargs):
        state = _require_state()
        if state.client is not None:
            # cache keyed by context: a shutdown/re-init must not reuse
            # a proxy bound to the old, disconnected session
            cached = getattr(self, "_client_fn", None)
            if cached is None or cached[0] is not state.client:
                cached = (state.client, state.client.remote(
                    self._fn, **_client_options(self._options)))
                self._client_fn = cached
            return cached[1].remote(*args, **kwargs)
        cw = state.core_worker
        key = self._ensure_pushed(cw)
        opts = self._options
        strategy, node_id, soft, pg_id, bundle_index = _strategy_fields(opts)
        streaming = opts["num_returns"] == "streaming"
        refs = cw.submit_task(
            key, args, kwargs,
            name=self._fn.__name__,
            num_returns=1 if streaming else opts["num_returns"],
            resources=_resource_dict(opts, default_cpu=1.0),
            max_retries=opts["max_retries"],
            strategy=strategy,
            node_id=node_id,
            soft=soft,
            placement_group_id=pg_id,
            bundle_index=bundle_index,
            streaming=streaming,
            runtime_env=_prepared_runtime_env(self, cw, opts),
        )
        if streaming:
            return refs  # an ObjectRefGenerator
        if opts["num_returns"] == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._fn.__name__}' cannot be called directly; "
            f"use .remote()."
        )


# ----------------------------------------------------------------------
# @remote — actors
# ----------------------------------------------------------------------


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns=None,
                concurrency_group: str = "") -> "ActorMethod":
        # None/"" mean "keep": chained .options calls must compose, not
        # silently reset each other's fields
        return ActorMethod(
            self._handle, self._name,
            self._num_returns if num_returns is None else num_returns,
            concurrency_group or self._concurrency_group)

    def remote(self, *args, **kwargs):
        cw = _require_state().core_worker
        streaming = self._num_returns == "streaming"
        refs = cw.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=1 if streaming else self._num_returns,
            streaming=streaming,
            concurrency_group=self._concurrency_group,
        )
        if streaming:
            return refs  # an ObjectRefGenerator
        if self._num_returns == 1:
            return refs[0]
        return refs


class ActorHandle:
    def __init__(self, actor_id: ActorID):
        self._actor_id = actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (_reconstruct_handle, (self._actor_id.binary(),))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:16]})"


def _reconstruct_handle(actor_id_bytes: bytes) -> ActorHandle:
    return ActorHandle(ActorID(actor_id_bytes))


class ActorClass:
    def __init__(self, cls, options: dict, class_key: bytes | None = None):
        self._cls = cls
        self._options = {**_OPTION_DEFAULTS, **options}
        self._class_key = class_key

    def options(self, **opts) -> "ActorClass":
        return ActorClass(self._cls, {**self._options, **opts}, self._class_key)

    def __reduce__(self):
        return (ActorClass, (self._cls, self._options, self._class_key))

    def remote(self, *args, **kwargs) -> ActorHandle:
        state = _require_state()
        if state.client is not None:
            cached = getattr(self, "_client_cls", None)
            if cached is None or cached[0] is not state.client:
                cached = (state.client, state.client.remote(
                    self._cls, **_client_options(self._options)))
                self._client_cls = cached
            return cached[1].remote(*args, **kwargs)
        cw = state.core_worker
        if self._class_key is None:
            self._class_key = cw.push_function(self._cls)
        opts = self._options
        strategy, node_id, soft, pg_id, bundle_index = _strategy_fields(opts)
        actor_id = cw.create_actor(
            self._class_key, args, kwargs,
            name=self._cls.__name__,
            actor_name=opts["name"],
            resources=_resource_dict(opts, default_cpu=1.0),
            max_restarts=opts["max_restarts"],
            max_concurrency=opts["max_concurrency"],
            concurrency_groups=opts["concurrency_groups"],
            detached=(opts["lifetime"] == "detached"),
            strategy=strategy,
            node_id=node_id,
            soft=soft,
            placement_group_id=pg_id,
            bundle_index=bundle_index,
            runtime_env=_prepared_runtime_env(self, cw, opts),
        )
        return ActorHandle(actor_id)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            f"directly; use .remote()."
        )


def method(*, concurrency_group: str = ""):
    """`@ray_tpu.method` on an actor method (reference `ray.method` +
    `concurrency_group_manager.h`): declares the named concurrency group
    the method runs in by default (callers can still override per call
    with `actor.m.options(concurrency_group=...)`). Multiple returns /
    streaming stay call-site options (`m.options(num_returns=...)`) —
    handles reconstruct from the actor id alone and carry no class
    metadata to read a declared default from."""

    def wrap(fn):
        if concurrency_group:
            fn.__ray_tpu_concurrency_group__ = concurrency_group
        return fn

    return wrap


def remote(*args, **kwargs):
    """`@remote` / `@remote(num_cpus=2, num_tpus=1, ...)` for functions and
    classes (reference: python/ray/__init__.py `ray.remote`)."""
    if len(args) == 1 and not kwargs and (
        inspect.isfunction(args[0]) or inspect.isclass(args[0])
    ):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target, {})

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    return wrap


def get_actor(name: str) -> ActorHandle:
    state = _require_state()
    if state.client is not None:
        return state.client.get_actor(name)
    cw = state.core_worker
    reply = cw._run_sync(cw.gcs.call("get_actor", {"name": name}))
    if not reply.get("found"):
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID(reply["actor_id"]))


# ----------------------------------------------------------------------
# scheduling strategies + placement groups
# ----------------------------------------------------------------------


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group: "PlacementGroup",
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundles = bundles

    def ready(self, timeout: float = 60.0) -> bool:
        cw = _require_state().core_worker
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply = cw._run_sync(cw.gcs.call(
                "get_placement_group", {"pg_id": self.id.binary()}
            ))
            if reply.get("found") and reply["state"] == "CREATED":
                return True
            if reply.get("found") and reply["state"] == "REMOVED":
                return False
            time.sleep(0.05)
        return False

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str | None = None,
                    topology: str | None = None) -> PlacementGroup:
    """Create a placement group.

    ``topology`` gang-places the bundles one-per-host onto a single
    complete TPU pod slice of that type (e.g. "v4-16"), atomically —
    bundle i lands on slice host i (see scheduling.place_slice_bundles;
    reference convention: python/ray/_private/accelerators/tpu.py:363-388
    promoted into the scheduler).
    """
    cw = _require_state().core_worker
    pg_id = PlacementGroupID.from_random()
    cw._run_sync(cw.gcs.call("create_placement_group", {
        "pg_id": pg_id.binary(),
        "bundles": bundles,
        "strategy": strategy,
        "name": name,
        "job_id": cw.job_id.binary(),
        "topology": topology,
    }))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    cw = _require_state().core_worker
    cw._run_sync(cw.gcs.call("remove_placement_group",
                             {"pg_id": pg.id.binary()}))


# ----------------------------------------------------------------------
# cluster introspection (reference: ray.nodes / cluster_resources)
# ----------------------------------------------------------------------


def nodes() -> List[dict]:
    cw = _require_state().core_worker
    raw = cw._run_sync(cw.gcs.call("get_nodes", {}))
    return [
        {
            "NodeID": n["node_id"].hex(),
            "Alive": n["alive"],
            "RayletAddr": n["raylet_addr"],
            "Resources": n["total"],
            "Available": n["available"],
        }
        for n in raw
    ]


def cluster_resources() -> Dict[str, float]:
    state = _require_state()
    if state.client is not None:
        return state.client.cluster_resources()
    totals: Dict[str, float] = {}
    for n in nodes():
        if n["Alive"]:
            for k, v in n["Resources"].items():
                totals[k] = totals.get(k, 0.0) + v
    return totals


def available_resources() -> Dict[str, float]:
    state = _require_state()
    if state.client is not None:
        return state.client.available_resources()
    totals: Dict[str, float] = {}
    for n in nodes():
        if n["Alive"]:
            for k, v in n["Available"].items():
                totals[k] = totals.get(k, 0.0) + v
    return totals


def list_actors() -> List[dict]:
    cw = _require_state().core_worker
    raw = cw._run_sync(cw.gcs.call("list_actors", {}))
    return [
        {
            "actor_id": a["actor_id"].hex(),
            "state": a["state"],
            "name": a["name"],
            "class_name": a.get("class_name"),
            "num_restarts": a["num_restarts"],
        }
        for a in raw
    ]
