"""Unique identifiers for jobs, tasks, actors, objects, nodes, and workers.

Design follows the reference's hash-derived ID scheme (`src/ray/common/id.h`):
ObjectIDs are derived from the TaskID that creates them plus a return index,
TaskIDs embed the parent ActorID (for actor tasks), and all IDs render as hex.
Sizes are fixed so IDs can live in shared-memory object tables (16 bytes).
"""

from __future__ import annotations

import hashlib
import os

ID_SIZE = 16  # bytes

_NIL = b"\xff" * ID_SIZE


class BaseID:
    __slots__ = ("_bytes",)
    _cache: dict = {}

    def __init__(self, binary: bytes):
        if len(binary) != ID_SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {ID_SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary

    @classmethod
    def from_random(cls):
        return cls(os.urandom(ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(_NIL)

    def is_nil(self) -> bool:
        return self._bytes == _NIL

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    __slots__ = ()

    @classmethod
    def from_int(cls, value: int):
        return cls(value.to_bytes(ID_SIZE, "big"))


class NodeID(BaseID):
    __slots__ = ()


class WorkerID(BaseID):
    __slots__ = ()


class ActorID(BaseID):
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", counter: int):
        h = hashlib.sha1()
        h.update(job_id.binary())
        h.update(parent_task_id.binary())
        h.update(counter.to_bytes(8, "big"))
        return cls(h.digest()[:ID_SIZE])


class PlacementGroupID(BaseID):
    __slots__ = ()


class TaskID(BaseID):
    """Task ids are structural: sha1(job, parent, actor)[:8] prefix +
    submission counter (5 bytes) + 3 zero bytes. The zero suffix is the
    keyspace `ObjectID.for_task_return` substitutes the return index
    into, so deriving a return id is a slice+concat instead of a hash —
    this pair is the hottest id math in the system (2 per task
    submission). The 64-bit prefix gives the same birthday-bound
    uniqueness story as the reference's hash-derived ids
    (`src/ray/common/id.h`), with counters disambiguating within a
    submitter context."""

    __slots__ = ()
    _prefix_cache: dict = {}

    @classmethod
    def for_driver(cls, job_id: JobID):
        h = hashlib.sha1(b"driver_task" + job_id.binary())
        return cls(h.digest()[:ID_SIZE])

    @classmethod
    def of(cls, job_id: JobID, parent_task_id: "TaskID", counter: int,
           actor_id: ActorID | None = None):
        key = (job_id._bytes, parent_task_id._bytes,
               None if actor_id is None else actor_id._bytes)
        prefix = cls._prefix_cache.get(key)
        if prefix is None:
            if len(cls._prefix_cache) > 4096:
                cls._prefix_cache.clear()  # workers churn parent contexts
            h = hashlib.sha1()
            h.update(job_id.binary())
            h.update(parent_task_id.binary())
            if actor_id is not None:
                h.update(actor_id.binary())
            prefix = cls._prefix_cache[key] = h.digest()[:8]
        return cls(prefix + counter.to_bytes(5, "big") + b"\x00\x00\x00")


class ObjectID(BaseID):
    __slots__ = ()

    @classmethod
    def for_task_return(cls, task_id: TaskID, return_index: int):
        # Slot the 1-based index into the task id's zero suffix (see
        # TaskID.of). Return ids and task ids live in disjoint keyspaces
        # everywhere they are stored, so index 0 colliding with the
        # task id itself would still be harmless — but 1-based keeps
        # them distinct anyway.
        return cls(task_id._bytes[:13]
                   + (return_index + 1).to_bytes(3, "big"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        h = hashlib.sha1()
        h.update(b"put")
        h.update(task_id.binary())
        h.update(put_index.to_bytes(4, "big"))
        return cls(h.digest()[:ID_SIZE])
