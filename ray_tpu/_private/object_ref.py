"""ObjectRef — a distributed future referencing an object owned by a worker.

Reference: `ObjectRef` in `python/ray/_raylet.pyx` + the ownership model of
`src/ray/core_worker/reference_count.h`: every object has exactly one owner
(the worker that created it); the ref carries the owner's address so any
holder can resolve status/location. Pickling a ref inside task args registers
the receiving worker as a borrower when deserialized.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private.ids import ObjectID

# Process-global hook: the active CoreWorker registers itself here so that
# ObjectRefs deserialized from task args / nested structures bind to it
# (reference: per-process Worker singleton in python/ray/_private/worker.py).
_context = threading.local()
_global_core_worker = None


def set_core_worker(cw) -> None:
    global _global_core_worker
    _global_core_worker = cw


def get_core_worker():
    return _global_core_worker


class ObjectRef:
    __slots__ = ("_id", "_owner_addr", "_weakref_slot", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: str = ""):
        self._id = object_id
        self._owner_addr = owner_addr
        cw = _global_core_worker
        if cw is not None:
            cw.register_ref(self)

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner_addr(self) -> str:
        return self._owner_addr

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        cw = _global_core_worker
        if cw is None:
            raise RuntimeError("ray_tpu is not initialized")
        return cw.as_future(self)

    def __await__(self):
        """Allow `await ref` inside async actors."""
        cw = _global_core_worker
        if cw is None:
            raise RuntimeError("ray_tpu is not initialized")
        return cw.await_ref(self).__await__()

    def __reduce__(self):
        return (_reconstruct_ref, (self._id.binary(), self._owner_addr))

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        cw = _global_core_worker
        if cw is not None:
            try:
                cw.deregister_ref(self)
            except Exception:
                pass


def _reconstruct_ref(id_bytes: bytes, owner_addr: str) -> ObjectRef:
    return ObjectRef(ObjectID(id_bytes), owner_addr)
