"""Task specification — the unit shipped from submitter to executor.

Equivalent of the reference's `TaskSpecification`
(`src/ray/common/task/task_spec.h:247`), kept msgpack-serializable so it can
ride the RPC layer without a separate proto toolchain. Args are a list of
entries, each either an inlined serialized value or an object reference
(top-level ObjectRef args become dependencies; the executor resolves them to
values before invoking the function — reference semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

NORMAL_TASK = "normal"
ACTOR_CREATION_TASK = "actor_creation"
ACTOR_TASK = "actor"

# Scheduling strategies (reference: python/ray/util/scheduling_strategies.py).
STRATEGY_DEFAULT = "DEFAULT"
STRATEGY_SPREAD = "SPREAD"
STRATEGY_NODE_AFFINITY = "NODE_AFFINITY"
STRATEGY_PLACEMENT_GROUP = "PLACEMENT_GROUP"


@dataclass
class TaskSpec:
    task_id: bytes
    job_id: bytes
    name: str
    task_type: str = NORMAL_TASK
    # Function: either a KV key into the GCS function table (normal path) or
    # an inline pickled callable (actor creation ships the class inline).
    function_key: Optional[bytes] = None
    # Serialized positional args: list of ("v", frame_bytes) | ("r", id, owner_addr).
    args: List = field(default_factory=list)
    # Serialized kwargs: {name: same entry form}.
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    owner_addr: str = ""
    owner_worker_id: bytes = b""
    # Actor fields.
    actor_id: Optional[bytes] = None
    method_name: Optional[str] = None
    seq_no: int = 0
    # Sequence epoch: bumped by the submitter whenever it restarts seq
    # numbering (actor restart OR reconnect after a connection loss), so the
    # executor can resynchronize its reorder buffer instead of waiting
    # forever on a seq that died with the old connection.
    seq_epoch: int = 0
    max_restarts: int = 0
    max_concurrency: int = 1
    # Scheduling.
    strategy: str = STRATEGY_DEFAULT
    node_id: Optional[bytes] = None  # NODE_AFFINITY target
    soft: bool = False
    placement_group_id: Optional[bytes] = None
    bundle_index: int = -1
    max_retries: int = 0
    runtime_env: Optional[dict] = None
    # Detached actors outlive their creator job.
    detached: bool = False
    actor_name: Optional[str] = None
    # Streaming generator task (num_returns="streaming"): the executor
    # reports each yielded item to the owner as it is produced
    # (reference: ReportGeneratorItemReturns, core_worker.proto:462).
    streaming: bool = False
    # Tracing context {trace_id, span_id} propagated submitter → executor
    # (reference: span context in task metadata, tracing_helper.py:326).
    trace_ctx: Optional[dict] = None

    def to_wire(self) -> dict:
        return {
            "task_id": self.task_id,
            "job_id": self.job_id,
            "name": self.name,
            "task_type": self.task_type,
            "function_key": self.function_key,
            "args": self.args,
            "kwargs": self.kwargs,
            "num_returns": self.num_returns,
            "resources": self.resources,
            "owner_addr": self.owner_addr,
            "owner_worker_id": self.owner_worker_id,
            "actor_id": self.actor_id,
            "method_name": self.method_name,
            "seq_no": self.seq_no,
            "seq_epoch": self.seq_epoch,
            "max_restarts": self.max_restarts,
            "max_concurrency": self.max_concurrency,
            "strategy": self.strategy,
            "node_id": self.node_id,
            "soft": self.soft,
            "placement_group_id": self.placement_group_id,
            "bundle_index": self.bundle_index,
            "max_retries": self.max_retries,
            "runtime_env": self.runtime_env,
            "detached": self.detached,
            "actor_name": self.actor_name,
            "streaming": self.streaming,
            "trace_ctx": self.trace_ctx,
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "TaskSpec":
        # msgpack round-trips lists as lists; args entries arrive as lists.
        return cls(**wire)

    def plasma_deps(self) -> List[tuple[bytes, str]]:
        """(object_id, owner_addr) for every by-reference arg."""
        deps = []
        for entry in self.args:
            if entry[0] == "r":
                deps.append((entry[1], entry[2]))
        for entry in self.kwargs.values():
            if entry[0] == "r":
                deps.append((entry[1], entry[2]))
        return deps

    def scheduling_key(self) -> tuple:
        """Tasks with the same key can reuse a cached worker lease
        (reference: SchedulingKey in direct_task_transport.h). The
        runtime env is part of the key: a lease's worker is materialized
        for ONE env, so tasks with different envs must never share a
        drain queue."""
        from ray_tpu._private.runtime_env import env_hash

        return (
            self.function_key,
            tuple(sorted(self.resources.items())),
            self.strategy,
            self.node_id,
            self.placement_group_id,
            self.bundle_index,
            env_hash(self.runtime_env),
        )
