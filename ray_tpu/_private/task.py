"""Task specification — the unit shipped from submitter to executor.

Equivalent of the reference's `TaskSpecification`
(`src/ray/common/task/task_spec.h:247`), kept msgpack-serializable so it can
ride the RPC layer without a separate proto toolchain. Args are a list of
entries, each either an inlined serialized value or an object reference
(top-level ObjectRef args become dependencies; the executor resolves them to
values before invoking the function — reference semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

NORMAL_TASK = "normal"
ACTOR_CREATION_TASK = "actor_creation"
ACTOR_TASK = "actor"

# Scheduling strategies (reference: python/ray/util/scheduling_strategies.py).
STRATEGY_DEFAULT = "DEFAULT"
STRATEGY_SPREAD = "SPREAD"
STRATEGY_NODE_AFFINITY = "NODE_AFFINITY"
STRATEGY_PLACEMENT_GROUP = "PLACEMENT_GROUP"


@dataclass(slots=True)
class TaskSpec:
    task_id: bytes
    job_id: bytes
    name: str
    task_type: str = NORMAL_TASK
    # Function: either a KV key into the GCS function table (normal path) or
    # an inline pickled callable (actor creation ships the class inline).
    function_key: Optional[bytes] = None
    # Serialized positional args: list of ("v", frame_bytes) | ("r", id, owner_addr).
    args: List = field(default_factory=list)
    # Serialized kwargs: {name: same entry form}.
    kwargs: Dict[str, Any] = field(default_factory=dict)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    owner_addr: str = ""
    owner_worker_id: bytes = b""
    # Actor fields.
    actor_id: Optional[bytes] = None
    method_name: Optional[str] = None
    seq_no: int = 0
    # Sequence epoch: bumped by the submitter whenever it restarts seq
    # numbering (actor restart OR reconnect after a connection loss), so the
    # executor can resynchronize its reorder buffer instead of waiting
    # forever on a seq that died with the old connection.
    seq_epoch: int = 0
    max_restarts: int = 0
    max_concurrency: int = 1
    # Scheduling.
    strategy: str = STRATEGY_DEFAULT
    node_id: Optional[bytes] = None  # NODE_AFFINITY target
    soft: bool = False
    placement_group_id: Optional[bytes] = None
    bundle_index: int = -1
    max_retries: int = 0
    runtime_env: Optional[dict] = None
    # Detached actors outlive their creator job.
    detached: bool = False
    actor_name: Optional[str] = None
    # Streaming generator task (num_returns="streaming"): the executor
    # reports each yielded item to the owner as it is produced
    # (reference: ReportGeneratorItemReturns, core_worker.proto:462).
    streaming: bool = False
    # Tracing context {trace_id, span_id} propagated submitter → executor
    # (reference: span context in task metadata, tracing_helper.py:326).
    trace_ctx: Optional[dict] = None
    # Named concurrency groups (reference:
    # src/ray/core_worker/transport/concurrency_group_manager.h):
    # creation carries {group_name: max_concurrency}; an actor task may
    # name the group it runs in ('' = the method's declared group, or
    # the default group).
    concurrency_groups: Optional[Dict[str, int]] = None
    concurrency_group: str = ""
    # Submitter-local only (never on the wire; must stay the LAST field
    # so `from_wire`'s positional splat fills exactly the wire fields):
    # the nested ObjectRefs found while serializing args, as
    # (object_id, owner_addr) pairs. Truthy ⇒ the spec must not ride a
    # multi-task batch (see CoreWorker._batchable); the pairs join
    # plasma_deps() so the owner pins them for the task's lifetime.
    _nested_refs: Any = False

    # Positional wire encoding: a flat msgpack array in field order.
    # Packing 29 values is ~3x cheaper than a 29-key string map (no key
    # strings packed/hashed per message), and this is the hottest
    # serialization in the system — every task submission ships one.
    _WIRE_FIELDS = (
        "task_id", "job_id", "name", "task_type", "function_key",
        "args", "kwargs", "num_returns", "resources", "owner_addr",
        "owner_worker_id", "actor_id", "method_name", "seq_no",
        "seq_epoch", "max_restarts", "max_concurrency", "strategy",
        "node_id", "soft", "placement_group_id", "bundle_index",
        "max_retries", "runtime_env", "detached", "actor_name",
        "streaming", "trace_ctx", "concurrency_groups",
        "concurrency_group",
    )

    def to_wire(self) -> list:
        return [
            self.task_id, self.job_id, self.name, self.task_type,
            self.function_key, self.args, self.kwargs, self.num_returns,
            self.resources, self.owner_addr, self.owner_worker_id,
            self.actor_id, self.method_name, self.seq_no, self.seq_epoch,
            self.max_restarts, self.max_concurrency, self.strategy,
            self.node_id, self.soft, self.placement_group_id,
            self.bundle_index, self.max_retries, self.runtime_env,
            self.detached, self.actor_name, self.streaming,
            self.trace_ctx, self.concurrency_groups,
            self.concurrency_group,
        ]

    @classmethod
    def from_wire(cls, wire) -> "TaskSpec":
        # msgpack round-trips lists as lists; args entries arrive as lists.
        return cls(*wire)

    def plasma_deps(self) -> List[tuple[bytes, str]]:
        """(object_id, owner_addr) for every by-reference arg — top-level
        entries plus (submitter side only) refs nested inside by-value
        containers. Wire-decoded specs carry no nested list, so executor/
        raylet callers see just the top-level deps."""
        deps = []
        for entry in self.args:
            if entry[0] == "r":
                deps.append((entry[1], entry[2]))
        for entry in self.kwargs.values():
            if entry[0] == "r":
                deps.append((entry[1], entry[2]))
        if isinstance(self._nested_refs, list):
            deps.extend(
                (oid, owner) for oid, owner in self._nested_refs)
        return deps

    def scheduling_key(self) -> tuple:
        """Tasks with the same key can reuse a cached worker lease
        (reference: SchedulingKey in direct_task_transport.h). The
        runtime env is part of the key: a lease's worker is materialized
        for ONE env, so tasks with different envs must never share a
        drain queue."""
        from ray_tpu._private.runtime_env import env_hash

        return (
            self.function_key,
            tuple(sorted(self.resources.items())),
            self.strategy,
            self.node_id,
            self.placement_group_id,
            self.bundle_index,
            env_hash(self.runtime_env),
        )


# from_wire unpacks positionally — the wire tuple and the dataclass field
# order must stay in lockstep (submitter-local fields trail the wire
# fields, defaulted) or every spec silently corrupts.
_LOCAL_FIELDS = ("_nested_refs",)
assert TaskSpec._WIRE_FIELDS + _LOCAL_FIELDS == tuple(
    f.name for f in TaskSpec.__dataclass_fields__.values()), \
    "TaskSpec._WIRE_FIELDS out of sync with field order"
