"""Deterministic fault-injection plane (the chaos plane).

Failure handling must be *provable*, not incidental: instead of ad-hoc
SIGKILLs scattered through tests, a seeded `FaultPlan` drives named
injection points threaded through the layers where faults actually land —

  * ``rpc.send`` / ``rpc.recv``   — ClientPool frame loss, delay,
    duplication (`rpc.py` RpcClient)
  * ``gcs.heartbeat``             — delayed / swallowed heartbeat handling
  * ``gcs.health``                — stalled health-check cycles
  * ``raylet.spawn``              — worker spawn failures (first k spawns
    raise a non-RuntimeEnvSetupError, exercising the crash-loop breaker)
  * ``raylet.lease``              — delayed lease dispatch
  * ``raylet.kill_node``          — abrupt node death after N heartbeats
  * ``core_worker.pull``          — delayed object pulls
  * ``train.pre_commit``          — kill a train rank in the window between
    its own shard persist and the gang checkpoint commit

Activation: the ``RAY_TPU_CHAOS`` env var, parsed once per process at
import (each daemon is its own process and reads its own env — a test can
scope a fault to one node by setting the var only around that node's
spawn), or programmatically via :func:`install`. With no plan active every
injection point is a single ``_PLAN is not None`` global check — the
module global stays ``None`` and the hot paths (``RpcClient.call_nowait``,
lease dispatch) pay one attribute load.

Determinism: every probabilistic site draws from its OWN
``random.Random`` stream seeded by ``(seed, site)``, so the decision
sequence at a site depends only on the seed and that site's draw count —
never on how sites interleave across the event loop. The decisions are
recorded in :attr:`FaultPlan.schedule` (capped), so the same seed replays
the identical fault schedule and any chaos failure reproduces exactly.

Reference ground: the reference's chaos utilities
(`python/ray/_private/test_utils.py` WorkerKillerActor,
`python/ray/tests/test_chaos.py`) are cadence-based and unseeded; this
plane makes the schedule a first-class, replayable artifact.

Grammar (``;``-separated ``key=value`` pairs)::

    RAY_TPU_CHAOS="seed=7;rpc_drop=0.05;rpc_delay=0.2:0.01;rpc_dup=0.1;
                   rpc_match=heartbeat|pull_object;
                   heartbeat_delay=0.5;heartbeat_drop=0.2;health_delay=0.1;
                   spawn_fail=2;lease_delay=0.5:0.02;pull_delay=0.3:0.01;
                   kill_node=heartbeats:6;commit_kill=1:1"

  - probabilities are plain floats in [0, 1]
  - delay values are ``p:seconds`` (probability p, fixed delay) or bare
    ``seconds`` (always)
  - ``rpc_match`` scopes every rpc_* fault to methods containing any of
    the ``|``-separated substrings (default: all methods)
  - ``spawn_fail=k`` fails the first k worker spawns of the process
  - ``kill_node=heartbeats:N`` makes the raylet ``os._exit(1)`` after its
    N-th successful heartbeat
  - ``commit_kill=rank:index`` kills a train worker whose session has no
    restore checkpoint (i.e. the first attempt) right after it persisted
    its shard for report ``index`` — inside the gang-commit window

Timed schedule (wall-clock faults, PR 8): ``at=`` entries layer faults
that fire at seeded wall-clock *offsets* instead of draw counts — the
injection trigger a multi-hour soak needs (a preemption lands at minute
37, not at heartbeat #6). Grammar::

    at=<offset_s>:<fault>[:<arg>][@<role>]

repeatable (``at=…;at=…``) or ``|``-separated inside one value. Faults:

  - ``kill``              — ``os._exit(1)`` at the offset (abrupt death)
  - ``crash_loop:<k>``    — re-arm ``spawn_fail`` for the next k spawns
  - ``hb_brownout:<dur>`` — drop every GCS heartbeat for ``dur`` seconds
  - ``data_stall:<dur>``  — data-plane block reads stall for ``dur`` s
  - ``ckpt_fail[:<n>]``   — next n checkpoint persists raise ChaosError
  - ``drop_objects[:<frac>]`` — force-delete a seeded random `frac`
    (default 0.5) of this node's sealed shm objects WITHOUT killing the
    process — object loss decoupled from node loss (exercises lineage
    reconstruction while the raylet keeps serving)

``@role`` scopes the entry to processes of that role (``driver``,
``gcs``, ``raylet``, ``worker``, ``train`` — the last arms at train
SESSION init, so it targets actual train ranks rather than idle task
workers); unscoped entries arm in any process. Entries arm when
:func:`set_role` (called by each daemon's ``__main__``) or
:meth:`FaultPlan.arm_timed` runs. Offsets are anchored to the
``RAY_TPU_CHAOS_EPOCH`` wall-clock timestamp when that env var is set
(the soak driver exports it at run start, so ``at=37`` means 37 s into
the SOAK regardless of when a restarted attempt re-arms the plan;
entries whose fire time already passed at arm are recorded as expired
and skipped); without the epoch, offsets run from arm time.
A daemon timer thread sleeps to each offset and fires it; state flips
happen under ``_timed_lock`` but the fire itself (record / export /
exit) runs OUTSIDE the lock — raylint's blocking-under-lock checker
flags the inverted shape. When ``RAY_TPU_CHAOS_LOG`` is set, each timed
entry is gated by a once-sentinel file in that directory so a fault
fires exactly once per soak run even though restarted attempts re-read
the same plan from the environment and re-arm it.

Post-mortem export: with ``RAY_TPU_CHAOS_LOG=<dir>`` every process
dumps its replay artifact (spec, seed, the ``(site, draw_seq,
decision)`` schedule, timed entries + actual fire timestamps) to
``chaos-<role>-<pid>.json`` at exit — including synchronously before
every ``os._exit`` path, which ``atexit`` would miss.
:meth:`FaultPlan.from_artifact` rebuilds the identical plan from an
artifact, so any soak failure replays exactly.
"""

from __future__ import annotations

import asyncio
import atexit
import json
import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_VAR = "RAY_TPU_CHAOS"
LOG_ENV = "RAY_TPU_CHAOS_LOG"
EPOCH_ENV = "RAY_TPU_CHAOS_EPOCH"
_LOG_CAP = 8192
# an entry armed after its anchored fire time fires anyway if it is at
# most this late (timer scheduling slop); later than this it expires
_ARM_GRACE_S = 1.0

_TIMED_FAULTS = ("kill", "crash_loop", "hb_brownout", "data_stall",
                 "ckpt_fail", "quota_flood", "drop_objects")
_ROLES = ("driver", "gcs", "raylet", "worker", "train")


class TimedFault(NamedTuple):
    """One wall-clock-scheduled fault: fires `offset` seconds after the
    plan is armed in a process whose role matches (None = any)."""
    offset: float
    fault: str
    arg: float
    role: Optional[str]


class ChaosError(RuntimeError):
    """An injected fault (deliberately NOT a RuntimeEnvSetupError: spawn
    chaos must exercise the generic spawn-failure path, including the
    crash-loop breaker's non-deterministic-exception counting)."""


def _parse_prob(value: str, key: str) -> float:
    p = float(value)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"{key}: probability {p} outside [0, 1]")
    return p


def _parse_delay(value: str, key: str) -> Tuple[float, float]:
    """'p:seconds' or bare 'seconds' (p=1)."""
    if ":" in value:
        p_str, s_str = value.split(":", 1)
        return _parse_prob(p_str, key), float(s_str)
    return 1.0, float(value)


def _parse_timed(value: str) -> List[TimedFault]:
    """Parse one ``at=`` value: ``|``-separated
    ``<offset>:<fault>[:<arg>][@<role>]`` entries."""
    out: List[TimedFault] = []
    for entry in filter(None, (e.strip() for e in value.split("|"))):
        role: Optional[str] = None
        body = entry
        if "@" in entry:
            body, role = entry.rsplit("@", 1)
            if role not in _ROLES:
                raise ValueError(
                    f"at: unknown role {role!r} (supported: {_ROLES})")
        parts = body.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"at: entry {entry!r} is not <offset>:<fault>[:<arg>]")
        offset = float(parts[0])
        fault = parts[1]
        if fault not in _TIMED_FAULTS:
            raise ValueError(f"at: unknown fault {fault!r} "
                             f"(supported: {_TIMED_FAULTS})")
        if fault == "kill":
            if len(parts) > 2:
                raise ValueError("at: kill takes no argument")
            arg = 0.0
        elif fault == "ckpt_fail":
            arg = float(parts[2]) if len(parts) > 2 else 1.0
        elif fault == "quota_flood":
            # window seconds; the flood hammers the registered target
            # (object-store puts) for the whole window
            arg = float(parts[2]) if len(parts) > 2 else 5.0
        elif fault == "drop_objects":
            # fraction of the node's sealed objects to force-delete
            arg = float(parts[2]) if len(parts) > 2 else 0.5
            if not 0.0 < arg <= 1.0:
                raise ValueError(
                    f"at: drop_objects fraction {arg} outside (0, 1]")
        else:  # crash_loop / hb_brownout / data_stall need an argument
            if len(parts) < 3:
                raise ValueError(f"at: {fault} requires an argument")
            arg = float(parts[2])
        out.append(TimedFault(offset, fault, arg, role))
    return out


class FaultPlan:
    """A parsed, seeded fault schedule. Immutable configuration +
    per-site deterministic RNG streams and draw counters."""

    def __init__(self, spec: str = ""):
        self.spec = spec
        self.seed = 0
        self.rpc_drop = 0.0
        self.rpc_dup = 0.0
        self.rpc_delay: Tuple[float, float] = (0.0, 0.0)
        self.rpc_recv_drop = 0.0
        self.rpc_recv_delay: Tuple[float, float] = (0.0, 0.0)
        self.rpc_match: Optional[Tuple[str, ...]] = None
        self.heartbeat_delay = 0.0
        self.heartbeat_drop = 0.0
        self.health_delay = 0.0
        self.spawn_fail = 0
        self.lease_delay: Tuple[float, float] = (0.0, 0.0)
        self.pull_delay: Tuple[float, float] = (0.0, 0.0)
        self.kill_node: Optional[Tuple[str, int]] = None
        self.commit_kill: Optional[Tuple[int, int]] = None
        self.timed: List[TimedFault] = []
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if "=" not in part:
                raise ValueError(f"chaos spec entry {part!r} is not key=value")
            key, value = part.split("=", 1)
            key, value = key.strip(), value.strip()
            if key == "seed":
                self.seed = int(value)
            elif key == "rpc_drop":
                self.rpc_drop = _parse_prob(value, key)
            elif key == "rpc_dup":
                self.rpc_dup = _parse_prob(value, key)
            elif key == "rpc_delay":
                self.rpc_delay = _parse_delay(value, key)
            elif key == "rpc_recv_drop":
                self.rpc_recv_drop = _parse_prob(value, key)
            elif key == "rpc_recv_delay":
                self.rpc_recv_delay = _parse_delay(value, key)
            elif key == "rpc_match":
                self.rpc_match = tuple(
                    m for m in value.split("|") if m) or None
            elif key == "heartbeat_delay":
                self.heartbeat_delay = float(value)
            elif key == "heartbeat_drop":
                self.heartbeat_drop = _parse_prob(value, key)
            elif key == "health_delay":
                self.health_delay = float(value)
            elif key == "spawn_fail":
                self.spawn_fail = int(value)
            elif key == "lease_delay":
                self.lease_delay = _parse_delay(value, key)
            elif key == "pull_delay":
                self.pull_delay = _parse_delay(value, key)
            elif key == "kill_node":
                if ":" in value:
                    unit, n = value.split(":", 1)
                else:
                    unit, n = "heartbeats", value
                if unit != "heartbeats":
                    raise ValueError(
                        f"kill_node: unknown trigger {unit!r} "
                        f"(supported: heartbeats:N)")
                self.kill_node = (unit, int(n))
            elif key == "commit_kill":
                rank, index = value.split(":", 1)
                self.commit_kill = (int(rank), int(index))
            elif key == "at":
                self.timed.extend(_parse_timed(value))
            else:
                raise ValueError(f"unknown chaos key {key!r}")
        self._send_active = (self.rpc_drop > 0 or self.rpc_dup > 0
                             or self.rpc_delay[0] > 0)
        self._recv_active = (self.rpc_recv_drop > 0
                             or self.rpc_recv_delay[0] > 0)
        self._rngs: Dict[str, random.Random] = {}
        self._counts: Dict[str, int] = {}
        # the replayable artifact: (site, draw_seq, decision)
        self.schedule: List[Tuple[str, int, str]] = []
        self._spawn_attempts = 0
        self._heartbeats_sent = 0
        # -- timed-schedule state (guarded by _timed_lock where noted) --
        self.installed_ts = time.time()
        self.timed_fired: List[Dict[str, Any]] = []
        self._timed_lock = threading.Lock()
        self._timed_stop = threading.Event()
        self._armed: set = set()           # indices into self.timed
        self._brownout_until = 0.0         # wall ts; write under lock
        self._stall_until = 0.0            # wall ts; write under lock
        self._ckpt_fail_pending = 0        # write under lock
        self._flood_until = 0.0            # wall ts; write under lock

    # -- deterministic draw machinery -----------------------------------

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # per-site stream: the decision sequence at one site is a pure
            # function of (seed, site, draw index) — event-loop interleaving
            # across sites cannot perturb it, which is what makes a chaos
            # failure replay exactly under the same seed
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def _record(self, site: str, decision: str) -> None:
        n = self._counts.get(site, 0)
        self._counts[site] = n + 1
        if len(self.schedule) < _LOG_CAP:
            self.schedule.append((site, n, decision))

    def _hit(self, site: str, p: float) -> bool:
        if p >= 1.0:
            return True
        return self._rng(site).random() < p

    def rng_for(self, site: str) -> random.Random:
        """Public per-site seeded stream for OTHER runtime randomness
        (scheduling tiebreaks, backoff jitter, …). Routing every
        probabilistic decision through a named site keeps the whole run
        a pure function of the seed — raylint's seeded-rng checker flags
        bare `random.*` in `_private/` for exactly this reason."""
        return self._rng(site)

    # -- rpc (ClientPool send/recv) -------------------------------------

    def _rpc_matches(self, method: str) -> bool:
        if self.rpc_match is None:
            return True
        return any(m in method for m in self.rpc_match)

    def rpc_send(self, method: str
                 ) -> Optional[Tuple[bool, bool, float]]:
        """(drop, dup, delay_s) for one outgoing frame, or None when no
        send faults apply to this method."""
        if not self._send_active or not self._rpc_matches(method):
            return None
        drop = self.rpc_drop > 0 and self._hit("rpc.send.drop", self.rpc_drop)
        dup = (not drop and self.rpc_dup > 0
               and self._hit("rpc.send.dup", self.rpc_dup))
        delay = 0.0
        dp, ds = self.rpc_delay
        if dp > 0 and self._hit("rpc.send.delay", dp):
            delay = ds
        if drop or dup or delay:
            self._record("rpc.send",
                         f"{method}:{'drop' if drop else ''}"
                         f"{'dup' if dup else ''}"
                         f"{f'delay={delay}' if delay else ''}")
            return (drop, dup, delay)
        return None

    def rpc_recv(self, method: str) -> Optional[Tuple[bool, float]]:
        """(drop, delay_s) for one incoming reply frame, or None."""
        if not self._recv_active or not self._rpc_matches(method):
            return None
        drop = (self.rpc_recv_drop > 0
                and self._hit("rpc.recv.drop", self.rpc_recv_drop))
        delay = 0.0
        dp, ds = self.rpc_recv_delay
        if dp > 0 and self._hit("rpc.recv.delay", dp):
            delay = ds
        if drop or delay:
            self._record("rpc.recv",
                         f"{method}:{'drop' if drop else ''}"
                         f"{f'delay={delay}' if delay else ''}")
            return (drop, delay)
        return None

    # -- gcs -------------------------------------------------------------

    async def gcs_heartbeat(self) -> bool:
        """Delay and/or swallow one heartbeat at the GCS handler. True
        means the heartbeat is dropped (handler must return without
        touching liveness state)."""
        if time.time() < self._brownout_until:
            self._record("gcs.heartbeat", "brownout-drop")
            return True
        if self.heartbeat_delay > 0:
            self._record("gcs.heartbeat", f"delay={self.heartbeat_delay}")
            await asyncio.sleep(self.heartbeat_delay)
        if self.heartbeat_drop > 0 and self._hit("gcs.heartbeat.drop",
                                                 self.heartbeat_drop):
            self._record("gcs.heartbeat", "drop")
            return True
        return False

    async def gcs_health_tick(self) -> None:
        """Stall one health-check cycle (models a wedged health checker:
        dead nodes detected late)."""
        if self.health_delay > 0:
            self._record("gcs.health", f"delay={self.health_delay}")
            await asyncio.sleep(self.health_delay)

    # -- raylet ----------------------------------------------------------

    def spawn_attempt(self) -> None:
        """Raise ChaosError for the first `spawn_fail` worker spawns of
        this raylet process."""
        # a timed crash_loop firing re-seeds spawn_fail/_spawn_attempts
        # from the schedule's timer thread — count under the same lock
        with self._timed_lock:
            if self.spawn_fail <= 0:
                return
            self._spawn_attempts += 1
            n, limit = self._spawn_attempts, self.spawn_fail
        if n <= limit:
            self._record("raylet.spawn", f"fail#{n}")
            raise ChaosError(
                f"chaos: injected worker spawn failure {n}/{limit}")

    async def lease_request(self) -> None:
        dp, ds = self.lease_delay
        if dp > 0 and self._hit("raylet.lease", dp):
            self._record("raylet.lease", f"delay={ds}")
            await asyncio.sleep(ds)

    def node_heartbeat_sent(self) -> None:
        """Abrupt node death: the raylet exits without any cleanup after
        its N-th successful heartbeat (models hardware loss — workers
        orphaned, arena left behind, GCS learns via missed heartbeats)."""
        if self.kill_node is None:
            return
        self._heartbeats_sent += 1
        if self._heartbeats_sent >= self.kill_node[1]:
            self._record("raylet.kill_node",
                         f"heartbeat#{self._heartbeats_sent}")
            logger.warning("chaos: killing node after %d heartbeats",
                           self._heartbeats_sent)
            self.export_artifact()  # atexit never runs past os._exit
            os._exit(1)

    # -- core worker -----------------------------------------------------

    async def object_pull(self) -> None:
        dp, ds = self.pull_delay
        if dp > 0 and self._hit("core_worker.pull", dp):
            self._record("core_worker.pull", f"delay={ds}")
            await asyncio.sleep(ds)

    # -- train session ---------------------------------------------------

    def train_pre_commit(self, world_rank: int, report_index: int,
                         fresh: bool) -> None:
        """Kill this rank between its own shard persist and the gang
        commit. Fires only on a session with no restore checkpoint
        (`fresh`), so the retried attempt survives the same plan."""
        if self.commit_kill is None or not fresh:
            return
        rank, index = self.commit_kill
        if world_rank == rank and report_index == index:
            self._record("train.pre_commit",
                         f"kill rank={rank} index={index}")
            logger.warning("chaos: killing rank %d before gang commit of "
                           "report %d", rank, index)
            self.export_artifact()  # atexit never runs past os._exit
            os._exit(1)

    def checkpoint_persist(self) -> None:
        """Raise ChaosError for the next `ckpt_fail` checkpoint persists
        (armed by the timed schedule). The failure propagates out of
        `report()` like a real storage fault, failing the attempt before
        the gang commit — the retry walks back to the last durable
        checkpoint."""
        fire = False
        with self._timed_lock:
            if self._ckpt_fail_pending > 0:
                self._ckpt_fail_pending -= 1
                fire = True
        if fire:
            self._record("train.ckpt_persist", "fail")
            raise ChaosError("chaos: injected checkpoint persist failure")

    # -- data plane ------------------------------------------------------

    def data_read_sync(self) -> None:
        """Synchronous data-source stall: block-read paths sleep out the
        remainder of an active `data_stall` window (models an ingest
        source brownout — object-store pulls stop completing)."""
        remaining = self._stall_until - time.time()
        if remaining > 0:
            self._record("data.read", f"stall={remaining:.3f}")
            time.sleep(remaining)

    # -- quota flood (multi-tenant overload containment) -----------------

    def flooding(self) -> bool:
        """True while a `quota_flood` window is active in this process."""
        return time.time() < self._flood_until

    def _quota_flood_run(self) -> None:
        """Hammer the registered flood target (an object-store put bound
        to this process's job — see set_quota_flood_target) for the
        window. The point is to PROVE containment: the offending job's
        puts get capped at its byte quota (rejections count up) while
        other jobs' objects and latency stay untouched."""
        puts = rejects = 0
        while not self._timed_stop.is_set() and \
                time.time() < self._flood_until:
            target = _FLOOD_TARGET
            if target is None:
                time.sleep(0.01)  # no store attached yet in this process
                continue
            try:
                target()
                puts += 1
            except Exception:  # noqa: BLE001 — QuotaExceeded/store full
                rejects += 1
            time.sleep(0.0005)  # hammer, but never a pure busy-spin
        self._record("timed.quota_flood.done",
                     f"puts={puts}:rejects={rejects}")

    # -- object loss (lineage recovery plane) ----------------------------

    def _drop_objects_run(self, frac: float) -> None:
        """Force-delete a seeded random `frac` of this node's sealed shm
        objects via the registered target (the raylet's store sweep —
        see set_drop_objects_target). The process survives: the point is
        object loss WITHOUT node loss, so lineage reconstruction gets
        exercised while leases, pulls and heartbeats keep flowing. The
        subset is drawn from the plan's own per-site stream, so the same
        seed always drops the same objects."""
        target = _DROP_TARGET
        if target is None:
            self._record("timed.drop_objects", "no-target")
            return
        try:
            dropped = target(frac, self.rng_for("timed.drop_objects"))
        except Exception:  # noqa: BLE001 — chaos must not kill the raylet
            logger.exception("chaos: drop_objects sweep failed")
            self._record("timed.drop_objects", "error")
            return
        self._record("timed.drop_objects", f"dropped={dropped}:frac={frac:g}")
        logger.warning("chaos: drop_objects force-deleted %d sealed objects "
                       "(frac=%g)", dropped, frac)

    # -- timed schedule (wall-clock offsets) -----------------------------

    def arm_timed(self, role: str) -> None:
        """Arm every not-yet-armed timed entry matching `role` (entries
        with no role match any process). Offsets are anchored to
        RAY_TPU_CHAOS_EPOCH when set (wall-clock soak time — a
        restarted attempt re-arming the plan keeps the original
        schedule), else to NOW. A daemon timer thread fires them.
        Idempotent per entry; entries already more than _ARM_GRACE_S
        past their anchored fire time expire instead of firing into the
        middle of a fresh attempt."""
        epoch = os.environ.get(EPOCH_ENV, "")
        now = time.time()
        try:
            base = float(epoch) if epoch else now
        except ValueError:
            base = now
        due: List[Tuple[int, TimedFault]] = []
        expired: List[TimedFault] = []
        with self._timed_lock:
            for i, tf in enumerate(self.timed):
                if i in self._armed:
                    continue
                if tf.role is not None and tf.role != role:
                    continue
                self._armed.add(i)
                if now - (base + tf.offset) > _ARM_GRACE_S:
                    expired.append(tf)
                else:
                    due.append((i, tf))
        for tf in expired:
            self._record(f"timed.{tf.fault}",
                         f"expired:t+{tf.offset:g}")
        if not due:
            return
        thread = threading.Thread(
            target=self._timed_run, args=(due, base),
            daemon=True, name=f"chaos-timed-{role}")
        thread.start()

    def _timed_run(self, due: List[Tuple[int, TimedFault]],
                   base: float) -> None:
        """Timer loop: sleep to each anchored fire time, then fire. All
        sleeping and firing happens OUTSIDE _timed_lock — only the
        state flip inside _fire_timed takes it."""
        for _, tf in sorted(due, key=lambda d: d[1].offset):
            while not self._timed_stop.is_set():
                remaining = base + tf.offset - time.time()
                if remaining <= 0:
                    break
                self._timed_stop.wait(min(remaining, 0.05))
            if self._timed_stop.is_set():
                return
            self._fire_timed(tf)

    def _fire_timed(self, tf: TimedFault) -> None:
        if not self._claim_once(tf):
            return
        now = time.time()
        with self._timed_lock:
            if tf.fault == "hb_brownout":
                self._brownout_until = now + tf.arg
            elif tf.fault == "data_stall":
                self._stall_until = now + tf.arg
            elif tf.fault == "ckpt_fail":
                self._ckpt_fail_pending += int(tf.arg)
            elif tf.fault == "crash_loop":
                self.spawn_fail = int(tf.arg)
                self._spawn_attempts = 0
            elif tf.fault == "quota_flood":
                self._flood_until = now + tf.arg
        # record / log / export / exit OUTSIDE the lock: _record appends,
        # export does file IO, and os._exit never returns
        self._record(f"timed.{tf.fault}", f"t+{tf.offset}:{tf.arg}")
        self.timed_fired.append(
            {"fault": tf.fault, "offset": tf.offset, "arg": tf.arg,
             "ts": now})
        logger.warning("chaos: timed fault %s fired at t+%.1fs (role=%s)",
                       tf.fault, tf.offset, _ROLE)
        if tf.fault == "quota_flood":
            threading.Thread(target=self._quota_flood_run,
                             daemon=True, name="chaos-quota-flood").start()
        if tf.fault == "drop_objects":
            threading.Thread(target=self._drop_objects_run, args=(tf.arg,),
                             daemon=True, name="chaos-drop-objects").start()
        if tf.fault == "kill":
            self.export_artifact()  # atexit never runs past os._exit
            os._exit(1)

    def _claim_once(self, tf: TimedFault) -> bool:
        """With RAY_TPU_CHAOS_LOG set, each timed entry fires exactly
        once per soak run — restarted attempts re-arm the same plan from
        the environment, and the sentinel file (atomic O_EXCL create)
        makes the re-armed copy a no-op. For `kill` the sentinel also
        picks a single victim when several processes of the role armed
        the same entry. Without a log dir, fire once per process."""
        log_dir = os.environ.get(LOG_ENV, "")
        if not log_dir:
            return True
        tag = f"{tf.fault}-{tf.offset:g}-{tf.role or 'any'}"
        try:
            os.makedirs(log_dir, exist_ok=True)
            fd = os.open(os.path.join(log_dir, f"once-{tag}"),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            return True
        except FileExistsError:
            return False
        except OSError:
            return True  # unwritable dir: fall back to per-process fire

    # -- post-mortem artifact -------------------------------------------

    def export_artifact(self, path: Optional[str] = None
                        ) -> Optional[str]:
        """Dump the replay artifact to JSON: the full spec + seed (enough
        to rebuild the plan), the (site, draw_seq, decision) schedule,
        and the timed entries with their actual fire timestamps. Default
        destination: `$RAY_TPU_CHAOS_LOG/chaos-<role>-<pid>.json`
        (no-op when neither a path nor the env dir is given)."""
        if path is None:
            log_dir = os.environ.get(LOG_ENV, "")
            if not log_dir:
                return None
            path = os.path.join(
                log_dir, f"chaos-{_ROLE}-{os.getpid()}.json")
        data = {
            "version": 1,
            "spec": self.spec,
            "seed": self.seed,
            "role": _ROLE,
            "pid": os.getpid(),
            "installed_ts": self.installed_ts,
            "exported_ts": time.time(),
            "schedule": [list(s) for s in self.schedule],
            "counts": dict(self._counts),
            "timed": [tf._asdict() for tf in self.timed],
            "timed_fired": list(self.timed_fired),
        }
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError:
            logger.exception("chaos: artifact export to %s failed", path)
            return None

    @classmethod
    def from_artifact(cls, path: str) -> "FaultPlan":
        """Rebuild the exact plan a previous run used from its exported
        artifact: same spec → same seed → same per-site decision streams
        and the same timed schedule, so the failure replays."""
        with open(path) as f:
            data = json.load(f)
        return cls(data["spec"])


# ---------------------------------------------------------------------------
# process-global plan
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ROLE = "driver"
_ATEXIT_REGISTERED = False
# quota_flood victimizer: a zero-arg callable that performs one
# job-stamped object-store put; registered by CoreWorker once a store is
# attached, consumed by FaultPlan._quota_flood_run
_FLOOD_TARGET = None


def set_quota_flood_target(fn) -> None:
    """Register (or clear, with None) this process's quota-flood target.
    The callable must do ONE put charged to the process's job and let
    QuotaExceededError propagate — the flood loop counts rejections."""
    global _FLOOD_TARGET
    _FLOOD_TARGET = fn


# drop_objects victimizer: `fn(frac, rng) -> int` force-deletes a
# seeded random `frac` of the node's sealed objects and returns the
# count; registered by the raylet once its store exists.
_DROP_TARGET = None


def set_drop_objects_target(fn) -> None:
    """Register (or clear, with None) this process's drop_objects
    target. The callable takes (fraction, random.Random) so the victim
    subset is a pure function of the plan seed, and returns how many
    objects it deleted."""
    global _DROP_TARGET
    _DROP_TARGET = fn


def plan() -> Optional[FaultPlan]:
    return _PLAN


def active() -> bool:
    return _PLAN is not None


def role() -> str:
    return _ROLE


def set_role(r: str) -> None:
    """Declare this process's role (driver/gcs/raylet/worker) — called by
    each daemon's `__main__` before serving. Arms any role-scoped (and
    still-unarmed unscoped) timed entries of the active plan; offsets
    run from now."""
    global _ROLE
    if r not in _ROLES:
        raise ValueError(f"unknown chaos role {r!r}")
    _ROLE = r
    if _PLAN is not None:
        _PLAN.arm_timed(r)


def _atexit_export() -> None:
    p = _PLAN
    if p is not None:
        p.export_artifact()


def install(p: FaultPlan) -> FaultPlan:
    global _PLAN, _ATEXIT_REGISTERED
    if _PLAN is not None:
        _PLAN._timed_stop.set()
    _PLAN = p
    if not _ATEXIT_REGISTERED:
        # registered once; the hook reads the CURRENT plan, so it also
        # covers plans installed later in this process
        atexit.register(_atexit_export)
        _ATEXIT_REGISTERED = True
    logger.warning("chaos plane active: %s", p.spec or "<programmatic>")
    return p


def uninstall() -> None:
    global _PLAN
    if _PLAN is not None:
        _PLAN._timed_stop.set()
    _PLAN = None


def init_from_env() -> Optional[FaultPlan]:
    """(Re)read RAY_TPU_CHAOS. Called at import; tests may call it again
    after mutating the environment."""
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        return install(FaultPlan(spec))
    uninstall()
    return None


init_from_env()
