"""Deterministic fault-injection plane (the chaos plane).

Failure handling must be *provable*, not incidental: instead of ad-hoc
SIGKILLs scattered through tests, a seeded `FaultPlan` drives named
injection points threaded through the layers where faults actually land —

  * ``rpc.send`` / ``rpc.recv``   — ClientPool frame loss, delay,
    duplication (`rpc.py` RpcClient)
  * ``gcs.heartbeat``             — delayed / swallowed heartbeat handling
  * ``gcs.health``                — stalled health-check cycles
  * ``raylet.spawn``              — worker spawn failures (first k spawns
    raise a non-RuntimeEnvSetupError, exercising the crash-loop breaker)
  * ``raylet.lease``              — delayed lease dispatch
  * ``raylet.kill_node``          — abrupt node death after N heartbeats
  * ``core_worker.pull``          — delayed object pulls
  * ``train.pre_commit``          — kill a train rank in the window between
    its own shard persist and the gang checkpoint commit

Activation: the ``RAY_TPU_CHAOS`` env var, parsed once per process at
import (each daemon is its own process and reads its own env — a test can
scope a fault to one node by setting the var only around that node's
spawn), or programmatically via :func:`install`. With no plan active every
injection point is a single ``_PLAN is not None`` global check — the
module global stays ``None`` and the hot paths (``RpcClient.call_nowait``,
lease dispatch) pay one attribute load.

Determinism: every probabilistic site draws from its OWN
``random.Random`` stream seeded by ``(seed, site)``, so the decision
sequence at a site depends only on the seed and that site's draw count —
never on how sites interleave across the event loop. The decisions are
recorded in :attr:`FaultPlan.schedule` (capped), so the same seed replays
the identical fault schedule and any chaos failure reproduces exactly.

Reference ground: the reference's chaos utilities
(`python/ray/_private/test_utils.py` WorkerKillerActor,
`python/ray/tests/test_chaos.py`) are cadence-based and unseeded; this
plane makes the schedule a first-class, replayable artifact.

Grammar (``;``-separated ``key=value`` pairs)::

    RAY_TPU_CHAOS="seed=7;rpc_drop=0.05;rpc_delay=0.2:0.01;rpc_dup=0.1;
                   rpc_match=heartbeat|pull_object;
                   heartbeat_delay=0.5;heartbeat_drop=0.2;health_delay=0.1;
                   spawn_fail=2;lease_delay=0.5:0.02;pull_delay=0.3:0.01;
                   kill_node=heartbeats:6;commit_kill=1:1"

  - probabilities are plain floats in [0, 1]
  - delay values are ``p:seconds`` (probability p, fixed delay) or bare
    ``seconds`` (always)
  - ``rpc_match`` scopes every rpc_* fault to methods containing any of
    the ``|``-separated substrings (default: all methods)
  - ``spawn_fail=k`` fails the first k worker spawns of the process
  - ``kill_node=heartbeats:N`` makes the raylet ``os._exit(1)`` after its
    N-th successful heartbeat
  - ``commit_kill=rank:index`` kills a train worker whose session has no
    restore checkpoint (i.e. the first attempt) right after it persisted
    its shard for report ``index`` — inside the gang-commit window
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_VAR = "RAY_TPU_CHAOS"
_LOG_CAP = 8192


class ChaosError(RuntimeError):
    """An injected fault (deliberately NOT a RuntimeEnvSetupError: spawn
    chaos must exercise the generic spawn-failure path, including the
    crash-loop breaker's non-deterministic-exception counting)."""


def _parse_prob(value: str, key: str) -> float:
    p = float(value)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"{key}: probability {p} outside [0, 1]")
    return p


def _parse_delay(value: str, key: str) -> Tuple[float, float]:
    """'p:seconds' or bare 'seconds' (p=1)."""
    if ":" in value:
        p_str, s_str = value.split(":", 1)
        return _parse_prob(p_str, key), float(s_str)
    return 1.0, float(value)


class FaultPlan:
    """A parsed, seeded fault schedule. Immutable configuration +
    per-site deterministic RNG streams and draw counters."""

    def __init__(self, spec: str = ""):
        self.spec = spec
        self.seed = 0
        self.rpc_drop = 0.0
        self.rpc_dup = 0.0
        self.rpc_delay: Tuple[float, float] = (0.0, 0.0)
        self.rpc_recv_drop = 0.0
        self.rpc_recv_delay: Tuple[float, float] = (0.0, 0.0)
        self.rpc_match: Optional[Tuple[str, ...]] = None
        self.heartbeat_delay = 0.0
        self.heartbeat_drop = 0.0
        self.health_delay = 0.0
        self.spawn_fail = 0
        self.lease_delay: Tuple[float, float] = (0.0, 0.0)
        self.pull_delay: Tuple[float, float] = (0.0, 0.0)
        self.kill_node: Optional[Tuple[str, int]] = None
        self.commit_kill: Optional[Tuple[int, int]] = None
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if "=" not in part:
                raise ValueError(f"chaos spec entry {part!r} is not key=value")
            key, value = part.split("=", 1)
            key, value = key.strip(), value.strip()
            if key == "seed":
                self.seed = int(value)
            elif key == "rpc_drop":
                self.rpc_drop = _parse_prob(value, key)
            elif key == "rpc_dup":
                self.rpc_dup = _parse_prob(value, key)
            elif key == "rpc_delay":
                self.rpc_delay = _parse_delay(value, key)
            elif key == "rpc_recv_drop":
                self.rpc_recv_drop = _parse_prob(value, key)
            elif key == "rpc_recv_delay":
                self.rpc_recv_delay = _parse_delay(value, key)
            elif key == "rpc_match":
                self.rpc_match = tuple(
                    m for m in value.split("|") if m) or None
            elif key == "heartbeat_delay":
                self.heartbeat_delay = float(value)
            elif key == "heartbeat_drop":
                self.heartbeat_drop = _parse_prob(value, key)
            elif key == "health_delay":
                self.health_delay = float(value)
            elif key == "spawn_fail":
                self.spawn_fail = int(value)
            elif key == "lease_delay":
                self.lease_delay = _parse_delay(value, key)
            elif key == "pull_delay":
                self.pull_delay = _parse_delay(value, key)
            elif key == "kill_node":
                if ":" in value:
                    unit, n = value.split(":", 1)
                else:
                    unit, n = "heartbeats", value
                if unit != "heartbeats":
                    raise ValueError(
                        f"kill_node: unknown trigger {unit!r} "
                        f"(supported: heartbeats:N)")
                self.kill_node = (unit, int(n))
            elif key == "commit_kill":
                rank, index = value.split(":", 1)
                self.commit_kill = (int(rank), int(index))
            else:
                raise ValueError(f"unknown chaos key {key!r}")
        self._send_active = (self.rpc_drop > 0 or self.rpc_dup > 0
                             or self.rpc_delay[0] > 0)
        self._recv_active = (self.rpc_recv_drop > 0
                             or self.rpc_recv_delay[0] > 0)
        self._rngs: Dict[str, random.Random] = {}
        self._counts: Dict[str, int] = {}
        # the replayable artifact: (site, draw_seq, decision)
        self.schedule: List[Tuple[str, int, str]] = []
        self._spawn_attempts = 0
        self._heartbeats_sent = 0

    # -- deterministic draw machinery -----------------------------------

    def _rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # per-site stream: the decision sequence at one site is a pure
            # function of (seed, site, draw index) — event-loop interleaving
            # across sites cannot perturb it, which is what makes a chaos
            # failure replay exactly under the same seed
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def _record(self, site: str, decision: str) -> None:
        n = self._counts.get(site, 0)
        self._counts[site] = n + 1
        if len(self.schedule) < _LOG_CAP:
            self.schedule.append((site, n, decision))

    def _hit(self, site: str, p: float) -> bool:
        if p >= 1.0:
            return True
        return self._rng(site).random() < p

    def rng_for(self, site: str) -> random.Random:
        """Public per-site seeded stream for OTHER runtime randomness
        (scheduling tiebreaks, backoff jitter, …). Routing every
        probabilistic decision through a named site keeps the whole run
        a pure function of the seed — raylint's seeded-rng checker flags
        bare `random.*` in `_private/` for exactly this reason."""
        return self._rng(site)

    # -- rpc (ClientPool send/recv) -------------------------------------

    def _rpc_matches(self, method: str) -> bool:
        if self.rpc_match is None:
            return True
        return any(m in method for m in self.rpc_match)

    def rpc_send(self, method: str
                 ) -> Optional[Tuple[bool, bool, float]]:
        """(drop, dup, delay_s) for one outgoing frame, or None when no
        send faults apply to this method."""
        if not self._send_active or not self._rpc_matches(method):
            return None
        drop = self.rpc_drop > 0 and self._hit("rpc.send.drop", self.rpc_drop)
        dup = (not drop and self.rpc_dup > 0
               and self._hit("rpc.send.dup", self.rpc_dup))
        delay = 0.0
        dp, ds = self.rpc_delay
        if dp > 0 and self._hit("rpc.send.delay", dp):
            delay = ds
        if drop or dup or delay:
            self._record("rpc.send",
                         f"{method}:{'drop' if drop else ''}"
                         f"{'dup' if dup else ''}"
                         f"{f'delay={delay}' if delay else ''}")
            return (drop, dup, delay)
        return None

    def rpc_recv(self, method: str) -> Optional[Tuple[bool, float]]:
        """(drop, delay_s) for one incoming reply frame, or None."""
        if not self._recv_active or not self._rpc_matches(method):
            return None
        drop = (self.rpc_recv_drop > 0
                and self._hit("rpc.recv.drop", self.rpc_recv_drop))
        delay = 0.0
        dp, ds = self.rpc_recv_delay
        if dp > 0 and self._hit("rpc.recv.delay", dp):
            delay = ds
        if drop or delay:
            self._record("rpc.recv",
                         f"{method}:{'drop' if drop else ''}"
                         f"{f'delay={delay}' if delay else ''}")
            return (drop, delay)
        return None

    # -- gcs -------------------------------------------------------------

    async def gcs_heartbeat(self) -> bool:
        """Delay and/or swallow one heartbeat at the GCS handler. True
        means the heartbeat is dropped (handler must return without
        touching liveness state)."""
        if self.heartbeat_delay > 0:
            self._record("gcs.heartbeat", f"delay={self.heartbeat_delay}")
            await asyncio.sleep(self.heartbeat_delay)
        if self.heartbeat_drop > 0 and self._hit("gcs.heartbeat.drop",
                                                 self.heartbeat_drop):
            self._record("gcs.heartbeat", "drop")
            return True
        return False

    async def gcs_health_tick(self) -> None:
        """Stall one health-check cycle (models a wedged health checker:
        dead nodes detected late)."""
        if self.health_delay > 0:
            self._record("gcs.health", f"delay={self.health_delay}")
            await asyncio.sleep(self.health_delay)

    # -- raylet ----------------------------------------------------------

    def spawn_attempt(self) -> None:
        """Raise ChaosError for the first `spawn_fail` worker spawns of
        this raylet process."""
        if self.spawn_fail <= 0:
            return
        self._spawn_attempts += 1
        if self._spawn_attempts <= self.spawn_fail:
            self._record("raylet.spawn",
                         f"fail#{self._spawn_attempts}")
            raise ChaosError(
                f"chaos: injected worker spawn failure "
                f"{self._spawn_attempts}/{self.spawn_fail}")

    async def lease_request(self) -> None:
        dp, ds = self.lease_delay
        if dp > 0 and self._hit("raylet.lease", dp):
            self._record("raylet.lease", f"delay={ds}")
            await asyncio.sleep(ds)

    def node_heartbeat_sent(self) -> None:
        """Abrupt node death: the raylet exits without any cleanup after
        its N-th successful heartbeat (models hardware loss — workers
        orphaned, arena left behind, GCS learns via missed heartbeats)."""
        if self.kill_node is None:
            return
        self._heartbeats_sent += 1
        if self._heartbeats_sent >= self.kill_node[1]:
            self._record("raylet.kill_node",
                         f"heartbeat#{self._heartbeats_sent}")
            logger.warning("chaos: killing node after %d heartbeats",
                           self._heartbeats_sent)
            os._exit(1)

    # -- core worker -----------------------------------------------------

    async def object_pull(self) -> None:
        dp, ds = self.pull_delay
        if dp > 0 and self._hit("core_worker.pull", dp):
            self._record("core_worker.pull", f"delay={ds}")
            await asyncio.sleep(ds)

    # -- train session ---------------------------------------------------

    def train_pre_commit(self, world_rank: int, report_index: int,
                         fresh: bool) -> None:
        """Kill this rank between its own shard persist and the gang
        commit. Fires only on a session with no restore checkpoint
        (`fresh`), so the retried attempt survives the same plan."""
        if self.commit_kill is None or not fresh:
            return
        rank, index = self.commit_kill
        if world_rank == rank and report_index == index:
            self._record("train.pre_commit",
                         f"kill rank={rank} index={index}")
            logger.warning("chaos: killing rank %d before gang commit of "
                           "report %d", rank, index)
            os._exit(1)


# ---------------------------------------------------------------------------
# process-global plan
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def plan() -> Optional[FaultPlan]:
    return _PLAN


def active() -> bool:
    return _PLAN is not None


def install(p: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = p
    logger.warning("chaos plane active: %s", p.spec or "<programmatic>")
    return p


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def init_from_env() -> Optional[FaultPlan]:
    """(Re)read RAY_TPU_CHAOS. Called at import; tests may call it again
    after mutating the environment."""
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        return install(FaultPlan(spec))
    uninstall()
    return None


init_from_env()
