"""Raylet — the per-node daemon: local scheduler, worker pool, object plane.

Reference: `src/ray/raylet/` — `NodeManager` (lease protocol + dispatch),
`WorkerPool` (spawns/pools per-job worker processes, `worker_pool.h:159`),
`LocalTaskManager` (dispatch queue), `DependencyManager` (pulls task args
into the local store), `PlacementGroupResourceManager` (bundle reservations),
plus the `ObjectManager` node-to-node transfer path
(`src/ray/object_manager/object_manager.h:117`). The shared-memory arena
(plasma) is created by this process and inherited by workers, exactly as the
reference embeds the plasma store in the raylet.

TPU-specific: the raylet owns the node's TPU chips as schedulable resources;
a lease that consumes `TPU` gets dedicated chips and the worker is spawned
with `TPU_VISIBLE_CHIPS` so JAX in that worker only initializes its chips
(reference sketch: python/ray/_private/accelerators/tpu.py).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private import accelerators
from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import health as health_mod
from ray_tpu._private import rpc as rpc_mod
from ray_tpu._private import task as task_mod
from ray_tpu._private.config import Config
from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.rpc import (
    ClientPool,
    ConnectionLost,
    ReconnectingClient,
    RpcError,
    RpcServer,
)
from ray_tpu._private import scheduling as scheduling_mod
from ray_tpu._private.scheduling import (
    ClusterView,
    FairDispatchQueue,
    SCHED_STATS,
    job_label,
    job_quota,
    pick_node,
)

logger = logging.getLogger(__name__)


@dataclass
class WorkerHandle:
    worker_id: bytes
    addr: str
    pid: int
    job_id: bytes
    proc: Optional[asyncio.subprocess.Process] = None
    tpu_chips: tuple = ()
    alive: bool = True
    # identity of the worker's materialized runtime env (reference:
    # per-runtime-env worker pools, worker_pool.h:159)
    env_hash: str = ""


@dataclass
class Lease:
    lease_id: int
    spec: task_mod.TaskSpec
    dedicated: bool
    reply_fut: asyncio.Future
    resources: Dict[str, float] = field(default_factory=dict)
    worker: Optional[WorkerHandle] = None
    deps_ready: bool = False
    acquired: bool = False
    pg_key: Optional[tuple] = None
    # already spilled here from another node — must not bounce again
    no_respill: bool = False


class Raylet:
    def __init__(
        self,
        gcs_addr: str,
        host: str = "127.0.0.1",
        port: int = 0,
        resources: Dict[str, float] | None = None,
        store_name: str | None = None,
        object_store_memory: int | None = None,
        config: Config | None = None,
        session_dir: str = "/tmp/ray_tpu",
        labels: Dict[str, str] | None = None,
    ):
        self.config = config or Config.from_env()
        self.node_id = NodeID.from_random()
        self.gcs_addr = gcs_addr
        # scale-envelope mode: leases satisfied by in-process stub
        # workers (see the virtual-workers section below)
        self.virtual_workers = \
            os.environ.get("RAY_TPU_VIRTUAL_WORKERS") == "1"
        self._none_frame: bytes | None = None
        self.server = RpcServer(host, port)
        self.clients = ClientPool()
        self.session_dir = session_dir

        # Slice membership: detect from the TPU-VM environment
        # (reference tpu.py metadata polling), with explicit labels
        # MERGED on top (per-key override). Replacing wholesale would
        # strip slice_type/host_id from autoscaled hosts — their
        # bootstrap passes only the autoscaler_instance label, and a
        # slice that registers without membership can never place the
        # topology gang that launched it.
        self.labels = dict(accelerators.slice_env() or {})
        if labels:
            self.labels.update(labels)
        if resources is not None:
            self.total = dict(resources)
        else:
            # no explicit resources: auto-detect like the reference's
            # accelerator managers (tpu.py:104-120 chip detection)
            self.total = {"CPU": float(os.cpu_count() or 1)}
            chips = accelerators.num_local_chips()
            if chips:
                self.total["TPU"] = float(chips)
        # host 0 of a slice carries the one-per-slice head resource
        # (reference tpu.py:363-388, promoted into the scheduler here)
        for k, v in accelerators.slice_resources(self.labels).items():
            self.total.setdefault(k, v)
        self.available = dict(self.total)
        # TPU chips are individually assignable; a chip is bound to a
        # worker process from spawn until that worker dies (a JAX process
        # owns its chips for its lifetime — chips cannot be handed between
        # live processes).
        n_tpu = int(self.total.get("TPU", 0))
        self.unassigned_chips: List[int] = list(range(n_tpu))

        self.store_name = store_name or f"/ray_tpu_{self.node_id.hex()[:12]}"
        self.store = ObjectStore.create(
            self.store_name,
            object_store_memory or self.config.object_store_memory,
            self.config.object_store_table_size,
        )

        # Worker pool state.
        self._idle: Dict[tuple, List[WorkerHandle]] = {}
        self._workers: Dict[bytes, WorkerHandle] = {}
        self._starting: Dict[tuple, int] = {}
        self._register_waiters: Dict[tuple, List[asyncio.Future]] = {}

        self._leases: Dict[int, Lease] = {}
        # Weighted-fair dispatch queue keyed by job: contended dispatch
        # drains per-job lanes in deficit-round-robin order (grant cost =
        # CPU+TPU demand over the job's quota weight) instead of global
        # FIFO, so one flooding tenant cannot starve the others.
        self._pending: FairDispatchQueue = FairDispatchQueue(
            cost_of=lambda lease: max(
                1.0,
                float(lease.resources.get("CPU", 0.0) or 0.0)
                + float(lease.resources.get("TPU", 0.0) or 0.0)))
        # Deadman probe for the dispatch drain. The drain is
        # event-driven on this loop, so liveness is proven two ways:
        # every _dispatch() pass beats, and a loop_ticker (started in
        # start()) beats between events — a blocked event loop freezes
        # both while the ticker's constant backlog keeps the deadman
        # armed. A quiet-but-healthy raylet keeps ticking.
        self._dispatch_probe = health_mod.watch_loop("raylet_dispatch")
        self._watchdog: Optional[health_mod.Watchdog] = None
        self._lease_seq = itertools.count(1)
        self._bundles: Dict[tuple, Dict[str, float]] = {}  # committed PG bundles
        self._bundle_available: Dict[tuple, Dict[str, float]] = {}
        self.view = ClusterView()
        self._bg: list = []
        self._spawned_procs: List[tuple] = []  # (proc, pool_key) pre-register
        # pool key -> consecutive deaths before registration (breaker)
        self._startup_failures: Dict[tuple, int] = {}
        self._pulls_inflight: Dict[bytes, asyncio.Future] = {}
        self._pinned: Dict[bytes, object] = {}  # oid -> held PlasmaBuffer
        # Disk spilling (reference: local_object_manager.h spill/restore):
        # pinned primary copies written to session-dir files so the shm
        # arena can hold more live data than its capacity.
        self._spilled: Dict[bytes, tuple] = {}  # oid -> (path, size)
        self._spill_dir = os.path.join(
            session_dir, f"spill-{self.node_id.hex()[:12]}")
        # serializes spill/restore disk work, which runs in executor
        # threads so multi-GB file I/O never stalls the event loop (and
        # with it the heartbeat that keeps this node alive)
        self._spill_lock = asyncio.Lock()
        # outbound-transfer leases: hold the buffer from meta to last
        # chunk so a pressured store cannot evict (and force re-restore
        # of) an object per chunk
        self._transfer_handles: Dict[bytes, object] = {}
        self._freed_since_heartbeat = False
        # wakes the heartbeat loop early when local resources free up —
        # the raylet->GCS half of push-based resource gossip
        self._heartbeat_nudge = asyncio.Event()
        # node_id -> monotonic time of its last push-delivered view
        # update (guards the heartbeat-reply prune against racing a
        # just-registered node's seed publish)
        self._view_push_ts: Dict[bytes, float] = {}
        # Raylet addresses the GCS has declared dead (resources-channel
        # dead publish). A pull must not spend a full connect timeout
        # discovering what the control plane already knows — known-dead
        # holders are reported to the owner immediately instead of
        # dialed. The owner's GCS-backed aliveness check is the
        # authority: a still_alive verdict un-poisons the entry.
        self._dead_addrs: Dict[str, float] = {}
        self._actor_workers: Dict[bytes, bytes] = {}  # worker_id -> actor_id
        # Memory-monitor kill records: owners query these to turn a
        # generic "worker died" into an actionable OutOfMemoryError
        # (reference: worker_killing_policy.h surfaces the policy's
        # reasoning in the task error).
        self._exit_reasons_by_addr: Dict[str, str] = {}
        # ownership-GC / recovery accounting
        self._objects_freed = 0   # owner refcount-zero deletions
        self._objects_dropped = 0  # chaos drop_objects force-deletes
        # drop_objects@raylet chaos victimizer: force-delete a seeded
        # subset of this node's sealed objects without killing the
        # process (silent object loss, as distinct from node death)
        _fi.set_drop_objects_target(self._chaos_drop_objects)

    # ------------------------------------------------------------------

    # lease-cycle counters (attribution: lease churn vs push batching —
    # the other half of the control-plane scrape next to rpc_coalescing)
    _leases_granted = 0
    _workers_returned = 0

    def _metrics_text(self) -> str:
        stats = self.store.stats()
        lines = [
            "# TYPE raylet_leases_granted counter",
            f"raylet_leases_granted {self._leases_granted}",
            f"raylet_workers_returned {self._workers_returned}",
            "# TYPE raylet_pending_leases gauge",
            f"raylet_pending_leases {len(self._pending)}",
            # alias under the cross-daemon name the flight-recorder
            # dashboards key on (same value as raylet_pending_leases)
            "# TYPE scheduler_queue_depth gauge",
            f"scheduler_queue_depth {len(self._pending)}",
        ]
        for job, depth in sorted(self._pending.depths().items()):
            lines.append(f'scheduler_queue_depth{{job="{job}"}} {depth}')
        lines += [
            f"raylet_workers {len(self._workers)}",
            f"raylet_pinned_objects {len(self._pinned)}",
            f"raylet_spilled_objects {len(self._spilled)}",
            "# TYPE raylet_objects_freed_total counter",
            f"raylet_objects_freed_total {self._objects_freed}",
            "# TYPE raylet_objects_dropped_total counter",
            f"raylet_objects_dropped_total {self._objects_dropped}",
            f"object_store_capacity_bytes {stats['capacity']}",
            f"object_store_allocated_bytes {stats['allocated']}",
            f"object_store_num_objects {stats['num_objects']}",
        ]
        for k, v in self.available.items():
            lines.append(
                f'raylet_resource_available{{resource="{k}"}} {v}')
        # sharded-store contention + per-shard rows, and the scheduling
        # decision counters — computed at scrape time
        return ("\n".join(lines) + "\n"
                + self.store.metrics_text()
                + scheduling_mod.metrics_text()
                + rpc_mod.metrics_text()
                + health_mod.metrics_text())

    async def start(self, metrics_port: int | None = None):
        self.server.register_all(self)
        await self.server.start()
        self._watchdog = health_mod.Watchdog(source="RAYLET").start()
        self._bg.append(health_mod.loop_ticker(self._dispatch_probe))
        if metrics_port is not None:
            from ray_tpu.util.metrics import serve_metrics

            self._metrics_server, port = await serve_metrics(
                port=metrics_port, extra_text=self._metrics_text)
            logger.info("metrics on :%d/metrics", port)
            self.metrics_port = port
        # reconnecting handle: survives a GCS restart (persistence FT)
        self.gcs = ReconnectingClient(self.clients, self.gcs_addr)
        await self.gcs.call("register_node", {
            "node_id": self.node_id.binary(),
            "raylet_addr": self.server.address,
            "total": self.total,
            "available": self.available,
            "hostname": os.uname().nodename,
            "labels": self.labels,
        })
        await self.gcs.call("subscribe",
                            {"channel": "jobs", "addr": self.server.address})
        # quotas of jobs that registered before this raylet joined: the
        # "started" publishes already happened, so pull the job table
        try:
            for jb in await self.gcs.call("list_jobs", {}, timeout=10.0):
                if not jb.get("finished"):
                    self._apply_job_quota(jb["job_id"], jb.get("quotas"))
        except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError):
            pass  # pubsub still delivers future jobs' quotas
        # push-based resource gossip: availability deltas arrive the
        # moment another node's heartbeat reports a change (reference:
        # ray_syncer.h:88 streaming sync), so spillback sees fresh state
        # instead of a view up to one heartbeat period stale
        await self.gcs.call("subscribe",
                            {"channel": "resources",
                             "addr": self.server.address})
        self.view.update_node(self.node_id.binary(), self.server.address,
                              self.total, self.available)
        self._heartbeat_nudge.set()  # first heartbeat immediately
        self._bg = [
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._reap_loop()),
        ]
        if self.config.memory_usage_threshold > 0:
            self._bg.append(
                asyncio.ensure_future(self._memory_monitor_loop()))
        logger.info("raylet %s on %s", self.node_id.hex()[:8], self.server.address)
        return self

    _metrics_server = None

    async def stop(self):
        for t in self._bg:
            t.cancel()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        for w in self._workers.values():
            if w.proc and w.proc.returncode is None:
                try:
                    w.proc.terminate()
                except ProcessLookupError:
                    pass
        await self.clients.close_all()
        await self.server.stop()
        self.store.destroy()
        import shutil

        shutil.rmtree(self._spill_dir, ignore_errors=True)

    @property
    def address(self) -> str:
        return self.server.address

    # ------------------------------------------------------------------
    # sync with GCS
    # ------------------------------------------------------------------

    async def _heartbeat_loop(self):
        last_sent = 0.0
        while True:
            # timer tick OR an on-change nudge (resources freed): the
            # nudge makes the raylet->GCS direction of the resource
            # gossip push-based too — freed capacity reaches the GCS
            # (and fans out to peer raylets) in milliseconds, not at
            # the next heartbeat period
            try:
                await asyncio.wait_for(
                    self._heartbeat_nudge.wait(),
                    self.config.raylet_heartbeat_period_s)
            except asyncio.TimeoutError:
                pass
            # Debounce nudged sends: a tight task stream frees
            # resources per completion, and a heartbeat + GCS delta
            # fan-out per task would tax the submission path it serves
            # (measured: -25% on single-client sync tasks). One nudged
            # heartbeat per 50ms coalesces bursts while keeping
            # freed-capacity propagation ~10x faster than the timer.
            gap = time.monotonic() - last_sent
            if gap < 0.05:
                await asyncio.sleep(0.05 - gap)
            self._heartbeat_nudge.clear()
            last_sent = time.monotonic()
            try:
                reply = await self.gcs.call("heartbeat", {
                    "node_id": self.node_id.binary(),
                    "available": self.available,
                    "idle_freed": self._freed_since_heartbeat,
                    # unmet lease demand, for the autoscaler's
                    # bin-packing (reference: ray_syncer resource-load
                    # gossip feeding GcsAutoscalerStateManager).
                    # Acquired leases hold local resources already —
                    # reporting them too would double-count the demand.
                    "pending_demands": [
                        lease.resources
                        for lease in self._pending.head(64)
                        if not lease.acquired
                    ],
                    # workers bound to actors or running leases (warm
                    # idle-pool workers excluded) — live actors hold no
                    # CPU resources, so idleness needs this signal
                    "busy_workers": len(self._workers) - sum(
                        len(p) for p in self._idle.values()),
                }, timeout=5.0)
                if _fi._PLAN is not None:
                    _fi._PLAN.node_heartbeat_sent()  # may os._exit(1)
                self._freed_since_heartbeat = False
                if reply.get("reregister"):
                    await self.gcs.call("register_node", {
                        "node_id": self.node_id.binary(),
                        "raylet_addr": self.server.address,
                        "total": self.total,
                        "available": self.available,
                        "labels": self.labels,
                    })
                for n in reply.get("view", []):
                    self.view.update_node(n["node_id"], n["raylet_addr"],
                                          n["total"], n["available"],
                                          labels=n.get("labels"))
                current = {n["node_id"] for n in reply.get("view", [])}
                now = time.monotonic()
                for node_id in list(self.view.nodes):
                    # prune nodes the GCS no longer reports — EXCEPT
                    # ones freshly seeded by a "resources" push, which
                    # may have registered after this reply's view was
                    # assembled (removing them would undo the push for
                    # a whole heartbeat period)
                    if node_id not in current and \
                            now - self._view_push_ts.get(node_id, 0.0) \
                            > 10.0:
                        self.view.remove_node(node_id)
                        self._view_push_ts.pop(node_id, None)
                self._respill_pending()
            except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError):
                # the nudge was cleared before the failed send: re-arm
                # it so the freed-capacity signal retries (debounce +
                # the RPC timeout bound the retry rate) instead of
                # silently waiting out a whole timer period
                self._heartbeat_nudge.set()

    def _respill_pending(self):
        """Hand queued leases that this node cannot currently satisfy to
        nodes that can (reference: ClusterTaskManager re-running cluster
        scheduling for queued work). This is what lets autoscaler-added
        nodes drain a backlog that queued before they existed."""
        for lease in list(self._pending):
            spec = lease.spec
            if spec.placement_group_id is not None:
                continue  # PG leases are bundle-bound to this node
            if lease.no_respill:
                continue  # spilled here once already — no ping-pong
            if lease.acquired:
                # resources already held locally (waiting on a worker
                # spawn): moving it now would leak the acquisition
                continue
            fits_local_now = all(
                self.available.get(k, 0.0) >= v
                for k, v in lease.resources.items() if v > 0)
            if fits_local_now:
                continue  # the normal dispatch path will take it
            node = pick_node(
                self.view, spec.resources, spec.strategy,
                local_node_id=self.node_id.binary(),
                target_node_id=spec.node_id,
                soft=spec.soft,
                spread_threshold=self.config.scheduler_spread_threshold,
            )
            if node is None or node.node_id == self.node_id.binary():
                continue
            self._pending.remove(lease)
            self._leases.pop(lease.lease_id, None)
            if not lease.reply_fut.done():
                lease.reply_fut.set_result({
                    "granted": False,
                    "spillback_addr": node.raylet_addr,
                })

    async def _reap_loop(self):
        """Detect dead worker processes (reference: WorkerPool monitors its
        children; NodeManager death-notifies the GCS for actors)."""
        while True:
            await asyncio.sleep(0.2)
            for worker in list(self._workers.values()):
                if worker.proc is not None and worker.proc.returncode is not None \
                        and worker.alive:
                    await self._on_worker_death(worker)
            # Workers that died before registering must release their
            # "starting" slot (and chips) or the pool stops replacing them.
            for entry in list(self._spawned_procs):
                proc, key = entry[0], entry[1]
                starting_key = entry[2] if len(entry) > 2 else key
                if proc.returncode is not None:
                    self._spawned_procs.remove(entry)
                    self._starting[starting_key] = max(
                        0, self._starting.get(starting_key, 0) - 1)
                    self.unassigned_chips.extend(key[1])
                    # Crash-loop breaker: a pool whose workers keep dying
                    # BEFORE registering (broken interpreter/runtime env)
                    # must not respawn forever — after a few consecutive
                    # startup deaths, fail the leases waiting on this key
                    # so callers see the error instead of a hang. Counted
                    # on starting_key, which for TPU pools is
                    # ("tpu", n_chips) — the CONCRETE chip tuple rotates
                    # between respawns and would dilute the count.
                    n = self._startup_failures.get(starting_key, 0) + 1
                    self._startup_failures[starting_key] = n
                    if n >= self.config.max_worker_startup_failures:
                        self._fail_leases_for_key(
                            starting_key,
                            f"worker startup crash-looped ({n} "
                            f"consecutive deaths before registration; "
                            f"see worker logs in the session dir)")
                    self._dispatch()

    # ------------------------------------------------------------------
    # host memory monitor (reference: memory_monitor.h:52 polls host
    # used/total; worker_killing_policy_group_by_owner.h picks victims)
    # ------------------------------------------------------------------

    def _host_memory_usage(self) -> tuple[int, int]:
        """(used_bytes, total_bytes). Reads the test-override file when
        configured ("used total"), else /proc/meminfo with used =
        MemTotal - MemAvailable (matches the reference's calculation)."""
        path = self.config.memory_usage_path
        if path:
            try:
                with open(path) as f:
                    used, total = f.read().split()
                return int(used), int(total)
            except (OSError, ValueError):
                return 0, 1
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    parts = line.split()
                    if parts[0] in ("MemTotal:", "MemAvailable:"):
                        info[parts[0]] = int(parts[1]) * 1024
            total = info.get("MemTotal:", 0)
            avail = info.get("MemAvailable:", total)
            return max(0, total - avail), max(1, total)
        except OSError:
            return 0, 1

    async def _memory_monitor_loop(self):
        period = self.config.memory_monitor_refresh_ms / 1000.0
        threshold = self.config.memory_usage_threshold
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(period)
            # /proc reads and the kill-selection walk both touch the
            # filesystem — keep the lease/heartbeat loop responsive
            used, total = await loop.run_in_executor(
                None, self._host_memory_usage)
            if used / total <= threshold:
                continue
            if await loop.run_in_executor(
                    None, self._relieve_memory_pressure, used, total):
                # give the reap loop + OS a cycle to reclaim the victim
                # before re-evaluating, or one spike kills every worker
                await asyncio.sleep(max(period, 0.5))

    def _relieve_memory_pressure(self, used: int, total: int) -> bool:
        """Free host memory, least harm first: an idle pooled worker
        (no task lost), else a leased task worker via group-by-owner
        (the owner with most running tasks loses its newest — retriable
        — one), else the newest actor worker. Returns True if a kill
        was issued."""
        from ray_tpu.util import events as export_events

        pct = f"{used / total:.0%}"
        header = (f"host memory {pct} ({used >> 20} MiB / "
                  f"{total >> 20} MiB) over threshold "
                  f"{self.config.memory_usage_threshold:.0%}")
        # 1) idle workers: reclaim without failing anything
        for pool in self._idle.values():
            while pool:
                worker = pool.pop()
                if worker.proc is not None and \
                        worker.proc.returncode is None:
                    export_events.report(
                        "RAYLET", "WARNING", "OOM_IDLE_WORKER_KILLED",
                        f"{header}; killed idle worker {worker.pid}",
                        node_id=self.node_id.hex())
                    worker.proc.kill()
                    return True
        # 2) leased (running-task) workers, grouped by owner
        running = [ls for ls in self._leases.values()
                   if ls.worker is not None and ls.worker.alive
                   and ls.worker.proc is not None
                   and ls.worker.proc.returncode is None]
        task_leases = [ls for ls in running
                       if ls.spec.task_type == task_mod.NORMAL_TASK]
        victim_lease = None
        if task_leases:
            groups: Dict[bytes, list] = {}
            for ls in task_leases:
                groups.setdefault(ls.spec.owner_worker_id, []).append(ls)
            biggest = max(groups.values(), key=len)
            # newest submission = highest lease id: the task that joined
            # the pressure last dies first (reference group-by-owner
            # kills the newest of the largest group)
            victim_lease = max(biggest, key=lambda ls: ls.lease_id)
            reason = (f"{header}; policy group-by-owner: owner "
                      f"{victim_lease.spec.owner_worker_id.hex()[:8]} has "
                      f"{len(biggest)} running task(s), killed the newest "
                      f"(task {victim_lease.spec.name!r}); the task is "
                      f"retriable and will be retried if retries remain")
        elif running:
            victim_lease = max(running, key=lambda ls: ls.lease_id)
            reason = (f"{header}; no retriable task to kill, killed the "
                      f"newest leased worker "
                      f"(task {victim_lease.spec.name!r})")
        if victim_lease is not None:
            worker = victim_lease.worker
            self._record_exit_reason(worker.addr, reason)
            export_events.report(
                "RAYLET", "WARNING", "OOM_WORKER_KILLED", reason,
                node_id=self.node_id.hex(), pid=worker.pid)
            worker.proc.kill()
            return True
        # 3) actor workers: newest registration dies first
        for worker_id in reversed(list(self._actor_workers)):
            worker = self._workers.get(worker_id)
            if worker is not None and worker.proc is not None \
                    and worker.proc.returncode is None:
                reason = (f"{header}; no task workers left, killed the "
                          f"newest actor worker (pid {worker.pid})")
                self._record_exit_reason(worker.addr, reason)
                export_events.report(
                    "RAYLET", "WARNING", "OOM_ACTOR_KILLED", reason,
                    node_id=self.node_id.hex(), pid=worker.pid)
                worker.proc.kill()
                return True
        return False

    def _record_exit_reason(self, addr: str, reason: str):
        # bounded: drop oldest so a long-lived raylet under periodic
        # pressure never grows this map without limit
        while len(self._exit_reasons_by_addr) >= 256:
            self._exit_reasons_by_addr.pop(
                next(iter(self._exit_reasons_by_addr)))
        self._exit_reasons_by_addr[addr] = reason

    async def rpc_get_worker_exit_reason(self, req):
        """Owner-side query: did the raylet kill this worker on purpose
        (memory monitor)? Lets the submitter surface OutOfMemoryError
        instead of a generic connection loss."""
        return {"reason": self._exit_reasons_by_addr.get(
            req["worker_addr"])}

    async def _on_worker_death(self, worker: WorkerHandle):
        from ray_tpu.util import events as export_events

        await export_events.report_async(
            "RAYLET", "WARNING", "WORKER_DIED",
            f"worker process {worker.pid} exited",
            worker_id=worker.worker_id.hex(), pid=worker.pid,
            node_id=self.node_id.hex())
        worker.alive = False
        self._workers.pop(worker.worker_id, None)
        self.unassigned_chips.extend(worker.tpu_chips)
        for pool in self._idle.values():
            if worker in pool:
                pool.remove(worker)
        # Free resources of any lease bound to this worker.
        for lease in list(self._leases.values()):
            if lease.worker is worker:
                self._release_lease(lease, worker_dead=True)
        actor_id = self._actor_workers.pop(worker.worker_id, None)
        if actor_id is not None:
            reason = self._exit_reasons_by_addr.get(
                worker.addr, f"worker process {worker.pid} exited")
            try:
                await self.gcs.call("report_actor_death", {
                    "actor_id": actor_id,
                    "reason": reason,
                })
            except (ConnectionLost, RpcError, OSError):
                pass
        self._dispatch()

    def _apply_job_quota(self, job_id: bytes, quotas: dict | None):
        """Install a job's quota row into both consumers on this node:
        the scheduler registry (weights + cpu/memory admission) and the
        shm store (object byte quota)."""
        if not quotas:
            return
        q = scheduling_mod.JobQuota.from_dict(quotas)
        scheduling_mod.set_job_quota(job_id, q)
        if q.object_store_bytes > 0:
            try:
                self.store.set_job_quota(job_id, q.object_store_bytes)
            except Exception:  # noqa: BLE001 — accounting table full
                logger.warning("object quota for job %s not applied "
                               "(job table full)", job_id.hex()[:8])

    async def rpc_pubsub(self, msg):
        if msg["channel"] == "jobs":
            data = msg["data"]
            if data.get("event") == "started":
                self._apply_job_quota(data["job_id"], data.get("quotas"))
            elif data.get("event") == "finished":
                job_id = data["job_id"]
                for worker in list(self._workers.values()):
                    if worker.job_id == job_id and worker.proc \
                            and worker.proc.returncode is None:
                        worker.proc.terminate()
        elif msg["channel"] == "resources":
            d = msg["data"]
            if d.get("node_id") == self.node_id.binary():
                return None  # our own state is authoritative locally
            if d.get("dead"):
                gone = self.view.nodes.get(d["node_id"])
                if gone is not None \
                        and gone.raylet_addr != self.server.address:
                    if len(self._dead_addrs) >= 256:
                        self._dead_addrs.pop(next(iter(self._dead_addrs)))
                    self._dead_addrs[gone.raylet_addr] = time.monotonic()
                    self.clients.invalidate(gone.raylet_addr)
                    self.clients.mark_dead(gone.raylet_addr)
                self.view.remove_node(d["node_id"])
                self._view_push_ts.pop(d["node_id"], None)
            else:
                self.view.update_node(d["node_id"], d["raylet_addr"],
                                      d["total"], d["available"],
                                      labels=d.get("labels"))
                self._view_push_ts[d["node_id"]] = time.monotonic()
                # fresh capacity elsewhere: queued leases that could not
                # place locally may spill NOW instead of next heartbeat
                self._respill_pending()
        return None

    # ------------------------------------------------------------------
    # worker pool
    # ------------------------------------------------------------------

    def _pool_key(self, job_id: bytes, tpu_chips: tuple,
                  env_hash: str = "") -> tuple:
        return (job_id, tpu_chips, env_hash)

    async def _spawn_worker(self, job_id: bytes, tpu_chips: tuple,
                            runtime_env: dict | None = None):
        python_exe = sys.executable
        if runtime_env and runtime_env.get("pip"):
            # venv build takes seconds — keep it off the raylet loop
            # (heartbeats must not stall). Cached by requirements hash,
            # so only the first worker of an env pays it.
            from ray_tpu._private import runtime_env as renv_mod
            python_exe = await asyncio.get_running_loop().run_in_executor(
                None, renv_mod.ensure_pip_env, runtime_env["pip"])
        elif runtime_env and runtime_env.get("conda"):
            # same off-loop treatment: conda env create can take minutes
            from ray_tpu._private import runtime_env as renv_mod
            python_exe = await asyncio.get_running_loop().run_in_executor(
                None, renv_mod.ensure_conda_env, runtime_env["conda"])
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        if runtime_env and runtime_env.get("env_vars"):
            env.update(runtime_env["env_vars"])
        if tpu_chips:
            env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in tpu_chips)
            env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
            # The raylet daemon runs with JAX_PLATFORMS=cpu; TPU workers
            # must get the machine's original platform back or JAX would
            # silently compute "TPU" tasks on host CPU.
            original = env.pop("RAY_TPU_WORKER_JAX_PLATFORMS", None)
            if original:
                env["JAX_PLATFORMS"] = original
            else:
                env.pop("JAX_PLATFORMS", None)
        else:
            # CPU-only workers must never grab the node's TPU chips.
            env["JAX_PLATFORMS"] = "cpu"
        log_dir = os.path.join(self.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"worker-{len(self._workers)}-{os.urandom(3).hex()}.log"
        )
        logfile = await asyncio.get_running_loop().run_in_executor(
            None, lambda: open(log_path, "ab"))
        proc = await asyncio.create_subprocess_exec(
            python_exe, "-m", "ray_tpu._private.worker_main",
            "--raylet-addr", self.server.address,
            "--gcs-addr", self.gcs_addr,
            "--store-name", self.store_name,
            "--node-id", self.node_id.hex(),
            "--job-id", job_id.hex(),
            "--tpu-chips", ",".join(str(c) for c in tpu_chips),
            "--runtime-env",
            json.dumps(runtime_env) if runtime_env else "",
            "--session-dir", self.session_dir,
            env=env,
            stdout=logfile,
            stderr=logfile,
        )
        logfile.close()
        return proc

    async def rpc_register_worker(self, req):
        # a fresh worker on a recycled host:port must not inherit a dead
        # worker's OOM-kill record (its own crash would be misreported)
        self._exit_reasons_by_addr.pop(req["addr"], None)
        worker = WorkerHandle(
            worker_id=req["worker_id"],
            addr=req["addr"],
            pid=req["pid"],
            job_id=req["job_id"],
            tpu_chips=tuple(req.get("tpu_chips", ())),
            env_hash=req.get("runtime_env_hash", ""),
        )
        # Adopt the subprocess handle if we spawned it.
        if worker.tpu_chips:
            key = self._pool_key(worker.job_id,
                                 ("tpu", len(worker.tpu_chips)),
                                 worker.env_hash)
        else:
            key = self._pool_key(worker.job_id, (), worker.env_hash)
        if self._starting.get(key):
            self._starting[key] -= 1
        key = self._pool_key(worker.job_id, worker.tpu_chips,
                             worker.env_hash)
        self._workers[worker.worker_id] = worker
        self._idle.setdefault(key, []).append(worker)
        # pool is healthy: reset the breaker under its counting key
        self._startup_failures.pop(
            self._pool_key(worker.job_id,
                           ("tpu", len(worker.tpu_chips))
                           if worker.tpu_chips else (),
                           worker.env_hash), None)
        self._match_worker_procs(worker)
        self._dispatch()
        return {"node_id": self.node_id.binary(), "store_name": self.store_name}

    def _match_worker_procs(self, worker: WorkerHandle):
        # Attach the asyncio Process object by pid for death detection.
        for entry in self._spawned_procs:
            if entry[0].pid == worker.pid:
                worker.proc = entry[0]
                self._spawned_procs.remove(entry)
                return

    # ------------------------------------------------------------------
    # lease protocol (reference: NodeManager::HandleRequestWorkerLease)
    # ------------------------------------------------------------------

    async def rpc_request_worker_lease(self, req):
        if _fi._PLAN is not None:
            await _fi._PLAN.lease_request()
        spec = task_mod.TaskSpec.from_wire(req["spec"])
        dedicated = bool(req.get("dedicated")) or \
            spec.task_type == task_mod.ACTOR_CREATION_TASK

        # Cluster-level decision: schedule here or spill back to another node.
        if spec.placement_group_id is None and not req.get("no_spillback"):
            if (spec.strategy == task_mod.STRATEGY_NODE_AFFINITY
                    and spec.node_id is not None
                    and spec.node_id != self.node_id.binary()):
                # Affinity routes to the target raylet — it is the
                # authority on its own resources and queues the lease if
                # busy. Deciding fit from our (possibly stale) view here
                # could wrongly run the task locally. The heartbeat-fed
                # view lags at startup, so an unknown target is resolved
                # against the GCS node table before concluding anything.
                target = self.view.nodes.get(spec.node_id)
                if target is None:
                    target = await self._refresh_view_for(spec.node_id)
                if target is not None and (
                        not spec.soft
                        or target.fits_now(spec.resources)):
                    # route to the target (hard always — it queues; soft
                    # only while it currently fits, else fall back)
                    return {"granted": False,
                            "spillback_addr": target.raylet_addr}
                if not spec.soft:
                    return {"granted": False,
                            "error": "affinity target node is dead"}
                # soft + target gone: fall through to the normal policy
                node = pick_node(
                    self.view, spec.resources, task_mod.STRATEGY_DEFAULT,
                    local_node_id=self.node_id.binary(),
                    spread_threshold=self.config.scheduler_spread_threshold,
                )
                if node is not None and node.node_id != self.node_id.binary():
                    return {"granted": False,
                            "spillback_addr": node.raylet_addr}
            else:
                node = pick_node(
                    self.view, spec.resources, spec.strategy,
                    local_node_id=self.node_id.binary(),
                    target_node_id=spec.node_id,
                    soft=spec.soft,
                    spread_threshold=self.config.scheduler_spread_threshold,
                )
                if node is not None and node.node_id != self.node_id.binary():
                    return {"granted": False,
                            "spillback_addr": node.raylet_addr}

        lease = Lease(
            lease_id=next(self._lease_seq),
            spec=spec,
            dedicated=dedicated,
            reply_fut=asyncio.get_event_loop().create_future(),
            resources=dict(spec.resources),
            no_respill=bool(req.get("no_spillback")),
        )
        if spec.placement_group_id is not None:
            lease.pg_key = (spec.placement_group_id, spec.bundle_index)
        self._leases[lease.lease_id] = lease
        self._pending.push(spec.job_id, lease)
        asyncio.ensure_future(self._localize_deps(lease))
        self._dispatch()
        return await lease.reply_fut

    async def _refresh_view_for(self, node_id: bytes):
        """Pull the authoritative node table from the GCS when a node is
        missing from the heartbeat-fed view (startup staleness)."""
        try:
            nodes = await self.gcs.call("get_nodes", {}, timeout=10.0)
        except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError):
            return None
        for n in nodes:
            if n["alive"]:
                self.view.update_node(n["node_id"], n["raylet_addr"],
                                      n["total"], n["available"],
                                      labels=n.get("labels"))
        return self.view.nodes.get(node_id)

    async def _localize_deps(self, lease: Lease):
        deps = lease.spec.plasma_deps()
        try:
            await asyncio.gather(*[
                self.pull_object(ObjectID(oid), owner) for oid, owner in deps
            ])
            lease.deps_ready = True
        except Exception as e:  # noqa: BLE001 — dep failure fails the lease
            if not lease.reply_fut.done():
                lease.reply_fut.set_result(
                    {"granted": False, "error": f"dependency fetch failed: {e}"}
                )
            if lease in self._pending:
                self._pending.remove(lease)
            self._leases.pop(lease.lease_id, None)
            return
        self._dispatch()

    def _try_acquire(self, lease: Lease) -> bool:
        """Deduct lease resources from the node pool (or its PG bundle)."""
        pool = self.available
        if lease.pg_key is not None:
            pg_id, bundle_index = lease.pg_key
            if bundle_index < 0:
                # Any bundle of this PG on this node that fits.
                demand = lease.resources
                for key, avail in self._bundle_available.items():
                    if key[0] == pg_id and all(
                        avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0
                    ):
                        lease.pg_key = key
                        break
                else:
                    return False
            pool = self._bundle_available.get(lease.pg_key)
            if pool is None:
                return False
        demand = lease.resources
        if not all(pool.get(k, 0.0) >= v for k, v in demand.items() if v > 0):
            return False
        for k, v in demand.items():
            pool[k] = pool.get(k, 0.0) - v
        lease.acquired = True
        return True

    def _release_resources(self, lease: Lease):
        if not lease.acquired:
            return
        pool = self.available
        if lease.pg_key is not None:
            pool = self._bundle_available.get(lease.pg_key)
            if pool is None:
                lease.acquired = False
                return
        for k, v in lease.resources.items():
            pool[k] = pool.get(k, 0.0) + v
        lease.acquired = False
        self._freed_since_heartbeat = True
        self._heartbeat_nudge.set()

    def _find_idle_tpu_worker(self, job_id: bytes, n_chips: int,
                              env_hash: str = ""):
        for key, pool in self._idle.items():
            if key[0] == job_id and len(key[1]) == n_chips \
                    and key[2] == env_hash and pool:
                return pool.pop()
        return None

    def _reclaim_idle_tpu_workers(self, needed: int):
        """Terminate idle TPU workers so their chips return to the
        unassigned pool (via the death path) when a pending lease needs a
        different chip grouping."""
        reclaimable = 0
        for key, pool in self._idle.items():
            if not key[1]:
                continue
            for worker in list(pool):
                if worker.proc is not None and worker.proc.returncode is None:
                    worker.proc.terminate()
                    pool.remove(worker)
                    reclaimable += len(worker.tpu_chips)
                    if reclaimable + len(self.unassigned_chips) >= needed:
                        return True
        return reclaimable > 0

    def _job_usage(self) -> Dict[bytes, Dict[str, float]]:
        """Resources currently held per job (acquired leases). Recomputed
        from the lease table each dispatch — O(leases), no incremental
        counters to tear when `_grant` rewrites an actor's held set."""
        usage: Dict[bytes, Dict[str, float]] = {}
        for lease in self._leases.values():
            if not lease.acquired:
                continue
            row = usage.setdefault(lease.spec.job_id, {})
            for k, v in lease.resources.items():
                row[k] = row.get(k, 0.0) + v
        return usage

    def _over_quota(self, job_id: bytes, demand: Dict[str, float],
                    usage: Dict[bytes, Dict[str, float]]) -> bool:
        """Admission control: would granting `demand` push the job past
        its cpu/memory quota? Over-quota leases stay queued behind
        in-quota work (containment degrades, never fails)."""
        q = job_quota(job_id)
        if q.cpu <= 0 and q.memory <= 0:
            return False
        held = usage.get(job_id, {})
        if q.cpu > 0 and held.get("CPU", 0.0) \
                + float(demand.get("CPU", 0.0) or 0.0) > q.cpu + 1e-9:
            return True
        if q.memory > 0 and held.get("memory", 0.0) \
                + float(demand.get("memory", 0.0) or 0.0) > q.memory + 1e-9:
            return True
        return False

    def _dispatch(self):
        """Dispatch queue scan in weighted-fair order (reference:
        LocalTaskManager::ScheduleAndDispatchTasks, drained through the
        per-job FairDispatchQueue instead of FIFO)."""
        from ray_tpu._private.runtime_env import env_hash as _env_hash

        self._dispatch_probe.beat()

        # key -> (shortfall count, runtime_env wire) for leases that hold
        # resources but lack a worker.
        spawn_needed: Dict[tuple, list] = {}
        usage = self._job_usage()
        for lease in list(self._pending):
            if not lease.deps_ready:
                continue
            job_id = lease.spec.job_id
            if not lease.acquired:
                if self._over_quota(job_id, lease.resources, usage):
                    label = job_label(job_id)
                    SCHED_STATS.job_deferred[label] = \
                        SCHED_STATS.job_deferred.get(label, 0) + 1
                    continue
                if not self._try_acquire(lease):
                    continue
                row = usage.setdefault(job_id, {})
                for k, v in lease.resources.items():
                    row[k] = row.get(k, 0.0) + v
            renv = lease.spec.runtime_env
            ehash = _env_hash(renv)
            n_chips = int(lease.resources.get("TPU", 0))
            if n_chips:
                worker = self._find_idle_tpu_worker(
                    lease.spec.job_id, n_chips, ehash)
                if worker is not None:
                    self._pending.charge(job_id, lease)
                    self._grant(lease, worker)
                    self._pending.remove(lease)
                    continue
                key = self._pool_key(lease.spec.job_id, ("tpu", n_chips),
                                     ehash)
                if self._starting.get(key, 0) > 0:
                    continue  # a matching worker is already starting
                if len(self.unassigned_chips) >= n_chips:
                    # Chips are reserved here, at spawn decision time, so
                    # two pending leases can never spawn workers holding
                    # the same chips.
                    chips = tuple(self.unassigned_chips[:n_chips])
                    del self.unassigned_chips[:n_chips]
                    self._starting[key] = self._starting.get(key, 0) + 1
                    asyncio.ensure_future(self._spawn_and_track(
                        (lease.spec.job_id, chips, ehash),
                        starting_key=key, runtime_env=renv))
                else:
                    self._reclaim_idle_tpu_workers(n_chips)
                continue
            key = self._pool_key(lease.spec.job_id, (), ehash)
            idle = self._idle.get(key, [])
            if idle:
                worker = idle.pop()
                self._pending.charge(job_id, lease)
                self._grant(lease, worker)
                self._pending.remove(lease)
            else:
                entry = spawn_needed.setdefault(key, [0, renv])
                entry[0] += 1
        # Spawn exactly the shortfall: workers already starting count against
        # the need, and total in-flight spawns are capped. The shortfall is
        # bounded by acquired resources, so a request flood cannot fork more
        # workers than the node has capacity for.
        for key, (needed, renv) in spawn_needed.items():
            starting = self._starting.get(key, 0)
            cap = self.config.maximum_startup_concurrency - starting
            for _ in range(max(0, min(needed - starting, cap))):
                self._starting[key] = self._starting.get(key, 0) + 1
                asyncio.ensure_future(
                    self._spawn_and_track(key, runtime_env=renv))

    async def _spawn_and_track(self, key: tuple,
                               starting_key: tuple | None = None,
                               runtime_env: dict | None = None):
        job_id, chips = key[0], key[1]
        starting_key = starting_key or key
        if self.virtual_workers:
            self._register_virtual_worker(job_id, chips, runtime_env,
                                          starting_key)
            return
        try:
            if _fi._PLAN is not None:
                _fi._PLAN.spawn_attempt()
            proc = await self._spawn_worker(job_id, chips, runtime_env)
        except Exception as e:
            logger.exception("worker spawn failed")
            self._starting[starting_key] = max(
                0, self._starting.get(starting_key, 0) - 1)
            self.unassigned_chips.extend(chips)
            from ray_tpu._private.runtime_env import RuntimeEnvSetupError
            if isinstance(e, RuntimeEnvSetupError):
                # a broken env spec fails deterministically: error out the
                # leases waiting on this env instead of respawning forever
                self._fail_leases_for_key(
                    key, f"runtime_env setup failed: {e}")
                return
            # Spawn-time exceptions that are NOT deterministic env errors
            # (transient OSError, unexpected backend failures, injected
            # chaos) feed the same crash-loop breaker as pre-registration
            # worker deaths: without this a persistently failing spawn
            # path would stall its leases until some unrelated event
            # re-triggered _dispatch, and a permanently failing one would
            # retry forever.
            n = self._startup_failures.get(starting_key, 0) + 1
            self._startup_failures[starting_key] = n
            if n >= self.config.max_worker_startup_failures:
                self._fail_leases_for_key(
                    starting_key,
                    f"worker spawn crash-looped ({n} consecutive spawn "
                    f"failures; last: {e})")
            else:
                self._dispatch()  # re-drive the shortfall spawn now
            return
        self._spawned_procs.append((proc, key, starting_key))

    # ------------------------------------------------------------------
    # virtual workers (scale-envelope mode)
    #
    # RAY_TPU_VIRTUAL_WORKERS=1 makes this raylet satisfy leases with
    # in-process stub workers instead of spawning real processes: the
    # raylet itself serves the worker RPC surface (push_task /
    # push_task_batch) at its own address, replying a packaged None per
    # return. The control plane — GCS tables, scheduler, gossip,
    # leases, placement groups — runs exactly as in production, which
    # is what the reference's scalability envelope measures
    # (release/benchmarks/README.md: 2k nodes / 40k actors / 10k tasks
    # with a TRIVIAL workload); only the workload processes are
    # virtualized so one box can host 50+ raylets and 5k+ actors.
    # ------------------------------------------------------------------

    def _register_virtual_worker(self, job_id: bytes, chips: tuple,
                                 runtime_env: dict | None,
                                 starting_key: tuple):
        from ray_tpu._private.runtime_env import env_hash as _env_hash

        worker = WorkerHandle(
            worker_id=os.urandom(16),
            addr=self.server.address,
            pid=0,
            job_id=job_id,
            tpu_chips=tuple(chips),
            env_hash=_env_hash(runtime_env),
        )
        self._starting[starting_key] = max(
            0, self._starting.get(starting_key, 0) - 1)
        key = self._pool_key(worker.job_id, worker.tpu_chips,
                             worker.env_hash)
        self._workers[worker.worker_id] = worker
        self._idle.setdefault(key, []).append(worker)
        self._dispatch()

    def _virtual_reply(self, spec: task_mod.TaskSpec) -> dict:
        if self._none_frame is None:
            from ray_tpu._private import serialization

            pickled, buffers = serialization.serialize(None)
            self._none_frame = serialization.pack(pickled, buffers)
        from ray_tpu._private.ids import TaskID

        returns = []
        for i in range(spec.num_returns):
            oid = ObjectID.for_task_return(TaskID(spec.task_id), i)
            returns.append([oid.binary(), "v", self._none_frame])
        return {"returns": returns}

    async def rpc_push_task(self, req):
        if not self.virtual_workers:
            return {"error": True,
                    "error_msg": "raylet does not execute tasks"}
        return self._virtual_reply(task_mod.TaskSpec.from_wire(req["spec"]))

    async def rpc_push_task_batch(self, req):
        if not self.virtual_workers:
            return [{"error": True,
                     "error_msg": "raylet does not execute tasks"}
                    for _ in req["specs"]]
        return [self._virtual_reply(task_mod.TaskSpec.from_wire(w))
                for w in req["specs"]]

    async def rpc_exit_worker(self, req):
        # Virtual workers share the raylet's address, so a kill_actor
        # notify lands here. There is no process to exit, but the
        # worker's lease (and any chips it holds) must still be
        # released or actor kill/create churn leaks node resources.
        wid = req.get("worker_id")
        if self.virtual_workers and wid:
            worker = self._workers.get(wid)
            if worker is not None:
                # the GCS initiated this exit and already marked the
                # actor dead — drop the mapping so the death handler
                # doesn't re-report it
                self._actor_workers.pop(wid, None)
                await self._on_worker_death(worker)
        return None

    def _fail_leases_for_key(self, key: tuple, msg: str) -> None:
        """Error out every pending lease whose (job, runtime env, chip
        demand) maps to this pool key — terminal action for the
        crash-loop breaker and for deterministic env-setup failures.
        Chip-scoped: a broken TPU pool must not fail the same job's
        healthy CPU leases (or vice versa)."""
        from ray_tpu._private.runtime_env import env_hash as _env_hash

        job_id = key[0]
        chips_key = key[1] if len(key) > 1 else ()
        ehash = key[2] if len(key) > 2 else ""
        if len(chips_key) == 2 and chips_key[0] == "tpu":
            want_tpu = int(chips_key[1])
        else:
            want_tpu = len(chips_key)
        for lease in list(self._pending):
            if lease.spec.job_id != job_id:
                continue
            if _env_hash(lease.spec.runtime_env) != ehash:
                continue
            if int(lease.resources.get("TPU", 0) or 0) != want_tpu:
                continue
            self._pending.remove(lease)
            self._release_resources(lease)
            self._leases.pop(lease.lease_id, None)
            if not lease.reply_fut.done():
                lease.reply_fut.set_result(
                    {"granted": False, "error": msg})
        # reset: a later, fixed env spec with the same key may succeed
        self._startup_failures.pop(key, None)

    def _grant(self, lease: Lease, worker: WorkerHandle):
        self._leases_granted += 1
        lease.worker = worker
        if lease.spec.task_type == task_mod.ACTOR_CREATION_TASK:
            self._actor_workers[worker.worker_id] = lease.spec.actor_id
            # Actors use their resources for *placement* but hold only
            # accelerators while alive (reference: actors hold 0 CPU after
            # creation, ray docs "actors use 1 CPU for scheduling and 0 for
            # running"); otherwise N live actors deadlock an N-CPU node.
            pool = self.available
            if lease.pg_key is not None:
                pool = self._bundle_available.get(lease.pg_key, pool)
            released = {k: v for k, v in lease.resources.items() if k != "TPU"}
            for k, v in released.items():
                pool[k] = pool.get(k, 0.0) + v
            lease.resources = {k: v for k, v in lease.resources.items()
                               if k == "TPU"}
            self._freed_since_heartbeat = True
            self._heartbeat_nudge.set()
        if not lease.reply_fut.done():
            lease.reply_fut.set_result({
                "granted": True,
                "worker_addr": worker.addr,
                "worker_id": worker.worker_id,
                "lease_id": lease.lease_id,
                "node_id": self.node_id.binary(),
            })

    def _release_lease(self, lease: Lease, worker_dead: bool = False):
        self._release_resources(lease)
        self._leases.pop(lease.lease_id, None)
        if lease in self._pending:
            self._pending.remove(lease)
        worker = lease.worker
        if worker is None:
            return
        if worker_dead:
            return
        if lease.dedicated:
            # Actor workers stay bound to the actor until it dies.
            return
        key = self._pool_key(worker.job_id, worker.tpu_chips,
                             worker.env_hash)
        self._idle.setdefault(key, []).append(worker)

    async def rpc_return_worker(self, req):
        self._workers_returned += 1
        lease = self._leases.get(req["lease_id"])
        if lease is None:
            return {"ok": False}
        worker = lease.worker
        self._release_lease(lease, worker_dead=req.get("worker_dead", False))
        if req.get("kill_worker") and worker is not None and worker.proc \
                and worker.proc.returncode is None:
            worker.proc.terminate()  # death path returns its chips/slots
        self._dispatch()
        return {"ok": True}

    # ------------------------------------------------------------------
    # placement group bundles
    # ------------------------------------------------------------------

    async def rpc_prepare_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        demand = req["resources"]
        if not all(self.available.get(k, 0.0) >= v for k, v in demand.items()):
            return {"ok": False}
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) - v
        self._bundles[key] = dict(demand)
        return {"ok": True}

    async def rpc_commit_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        if key not in self._bundles:
            return {"ok": False}
        self._bundle_available[key] = dict(self._bundles[key])
        self._dispatch()
        return {"ok": True}

    async def rpc_release_bundle(self, req):
        key = (req["pg_id"], req["bundle_index"])
        demand = self._bundles.pop(key, None)
        self._bundle_available.pop(key, None)
        if demand:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
            self._freed_since_heartbeat = True
            self._heartbeat_nudge.set()
        self._dispatch()
        return {"ok": True}

    # ------------------------------------------------------------------
    # object plane (DependencyManager + ObjectManager)
    # ------------------------------------------------------------------

    async def pull_object(self, object_id: ObjectID, owner_addr: str):
        """Ensure `object_id` is in the local store, fetching (or
        restoring from local spill) if needed."""
        if self.store.contains(object_id):
            return
        if await self._restore_async(object_id.binary()):
            return
        inflight = self._pulls_inflight.get(object_id.binary())
        if inflight is not None:
            await inflight
            return
        fut = asyncio.get_event_loop().create_future()
        self._pulls_inflight[object_id.binary()] = fut
        try:
            await self._pull_with_recovery(object_id, owner_addr)
            fut.set_result(True)
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            if not fut.done():
                fut.set_result(True)
            # The entry only dedupes concurrent pulls; once settled it must
            # go away or a later re-pull (after eviction) would no-op on the
            # stale completed future.
            self._pulls_inflight.pop(object_id.binary(), None)

    async def _pull_with_recovery(self, object_id: ObjectID,
                                  owner_addr: str, attempts: int = 8):
        """Fetch from an advertised location; on failure report the dead
        location to the owner (who drops it and, for reconstructible
        objects, re-executes the creating task — reference:
        ObjectRecoveryManager) and re-query. The status query blocks
        while the owner reconstructs, so the retry lands on the fresh
        copy."""
        owner = await self.clients.get(owner_addr)
        last_err = "no locations"
        for _ in range(attempts):
            status = await owner.call("get_object_status", {
                "object_id": object_id.binary(),
                "wait": True,
            }, timeout=300.0)
            if status.get("error"):
                raise RuntimeError(status["error"])
            if self.store.contains(object_id):
                return
            if status["status"] == "inband":
                await self._put_raw_with_spill_async(object_id,
                                                     status["value"])
                return
            if status["status"] == "err":
                # error frames surface at the caller's get(); nothing to
                # localize
                raise RuntimeError("object errored at owner")
            all_locs = status.get("locations", [])
            locations = [a for a in all_locs if a != self.server.address]
            if not locations:
                if self.server.address in all_locs:
                    # the owner thinks WE hold it, but we don't (evicted
                    # or lost): this report is authoritative — no GCS
                    # liveness check can refute a raylet about its own
                    # store
                    await owner.call("report_lost_location", {
                        "object_id": object_id.binary(),
                        "raylet_addr": self.server.address,
                        "authoritative": True,
                    }, timeout=30.0)
                last_err = f"no locations for {object_id.hex()}"
                await asyncio.sleep(0.5)
                continue
            fetched = False
            for addr in locations:
                if addr in self._dead_addrs:
                    # GCS already declared this holder dead: skip the
                    # dial (a cold connect costs the full
                    # rpc_connect_timeout_s) and go straight to the
                    # lost-location report so the owner reconstructs
                    fetched = False
                else:
                    try:
                        fetched = await self._fetch_remote_chunked(
                            object_id, addr)
                    except (ConnectionLost, RpcError, OSError,
                            RuntimeError):
                        fetched = False
                if fetched:
                    await owner.notify("add_object_location", {
                        "object_id": object_id.binary(),
                        "raylet_addr": self.server.address,
                    })
                    break
                last_err = f"fetch failed from {addr}"
                verdict = await owner.call("report_lost_location", {
                    "object_id": object_id.binary(),
                    "raylet_addr": addr,
                }, timeout=30.0)
                if verdict.get("still_alive"):
                    # transient blip to a live holder — or a dead node
                    # the GCS hasn't pruned yet (prune takes ~period ×
                    # threshold). Back off long enough that the attempt
                    # budget comfortably spans that window.
                    self._dead_addrs.pop(addr, None)
                    await asyncio.sleep(1.0)
            if fetched:
                return
        raise RuntimeError(
            f"pull failed for {object_id.hex()}: {last_err}")

    async def rpc_pull_object(self, req):
        await self.pull_object(ObjectID(req["object_id"]), req["owner_addr"])
        return {"ok": True}

    # -- chunked transfer (reference: ObjectBufferPool chunking,
    # object_manager.h:117 — fixed-size chunks pipelined into a
    # pre-created buffer, so object size is not capped by the RPC frame
    # limit and no whole-object intermediate copy is made) -------------

    async def _buffer_or_restore(self, oid_bytes: bytes):
        buf = self.store.get_buffer(ObjectID(oid_bytes), timeout=-1)
        if buf is None:
            try:
                restored = await self._restore_async(oid_bytes)
            except Exception as e:  # noqa: BLE001
                logger.warning("restore of %s failed: %r",
                               oid_bytes.hex()[:12], e)
                return None
            if restored:
                buf = self.store.get_buffer(ObjectID(oid_bytes),
                                            timeout=-1)
            else:
                logger.info("object %s neither in store nor spilled",
                            oid_bytes.hex()[:12])
        return buf

    def _release_transfer_handle(self, oid_bytes: bytes):
        self._transfer_handles.pop(oid_bytes, None)

    async def rpc_fetch_object_meta(self, req):
        oid = req["object_id"]
        buf = await self._buffer_or_restore(oid)
        if buf is None:
            return {"size": None}
        # transfer lease: keep the buffer referenced (unevictable) while
        # the puller streams chunks; reaped on a timer as a backstop
        self._transfer_handles[oid] = buf
        asyncio.get_event_loop().call_later(
            300.0, self._release_transfer_handle, oid)
        return {"size": buf.nbytes}

    async def rpc_fetch_object_chunk(self, req):
        oid = req["object_id"]
        buf = self._transfer_handles.get(oid)
        if buf is None:
            buf = await self._buffer_or_restore(oid)
        if buf is None:
            return {"data": None}
        off = req["offset"]
        data = bytes(buf[off:off + req["length"]])
        if req.get("last"):
            self._release_transfer_handle(oid)
        return {"data": data}

    async def _fetch_remote_chunked(self, object_id: ObjectID,
                                    addr: str) -> bool:
        """Stream a remote object in pipelined chunks directly into a
        pre-created local shm buffer; returns False when the holder no
        longer has the object."""
        holder = await self.clients.get(addr)
        meta = await holder.call(
            "fetch_object_meta", {"object_id": object_id.binary()},
            timeout=60.0)
        size = meta.get("size")
        if size is None:
            return False
        buf = await self._create_with_spill_async(object_id, size)
        chunk = self.config.object_transfer_chunk_bytes
        sem = asyncio.Semaphore(self.config.object_transfer_parallelism)

        offsets = list(range(0, size, chunk))
        remaining = {"n": len(offsets)}

        async def fetch_one(off: int):
            async with sem:
                remaining["n"] -= 1
                reply = await holder.call("fetch_object_chunk", {
                    "object_id": object_id.binary(),
                    "offset": off,
                    "length": min(chunk, size - off),
                    # releases the holder's transfer lease with the
                    # final chunk request
                    "last": remaining["n"] == 0,
                }, timeout=300.0)
                data = reply.get("data")
                if data is None:
                    raise RuntimeError("holder dropped object mid-fetch")
                buf[off:off + len(data)] = data

        try:
            await asyncio.gather(*[fetch_one(off) for off in offsets])
        except BaseException:
            try:
                self.store.release(object_id)
                self.store.delete(object_id)  # discard the partial write
            except Exception:  # noqa: BLE001
                pass
            raise
        self.store.seal(object_id)
        self.store.release(object_id)
        return True

    # -- spilling / restore (reference: local_object_manager.h:41).
    # All whole-object disk I/O runs in executor threads under
    # _spill_lock: the raylet loop must keep heartbeating while
    # multi-GB files move, or the GCS declares this node dead. --------

    def _create_with_spill(self, object_id: ObjectID, size: int):
        """Synchronous create-with-spill; call from an executor thread
        (or via _create_with_spill_async from the loop)."""
        from ray_tpu._private.object_store import ObjectStoreFullError

        for _ in range(3):
            try:
                return self.store.create_buffer(object_id, size)
            except ObjectStoreFullError:
                if self._spill_up_to(size) == 0:
                    raise
        return self.store.create_buffer(object_id, size)

    async def _create_with_spill_async(self, object_id: ObjectID,
                                       size: int):
        from ray_tpu._private.object_store import ObjectStoreFullError

        try:
            return self.store.create_buffer(object_id, size)
        except ObjectStoreFullError:
            pass
        async with self._spill_lock:
            return await asyncio.get_event_loop().run_in_executor(
                None, self._create_with_spill, object_id, size)

    def _put_raw_with_spill(self, object_id: ObjectID, data) -> None:
        buf = self._create_with_spill(object_id, len(data))
        buf[:] = data
        self.store.seal(object_id)
        self.store.release(object_id)

    async def _put_raw_with_spill_async(self, object_id: ObjectID,
                                        data) -> None:
        from ray_tpu._private.object_store import ObjectStoreFullError

        try:
            self.store.put_raw(object_id, data)
            return
        except ObjectStoreFullError:
            pass
        async with self._spill_lock:
            await asyncio.get_event_loop().run_in_executor(
                None, self._put_raw_with_spill, object_id, data)

    def _spill_up_to(self, needed: int) -> int:
        """Write pinned primary copies to disk (oldest pin first) until
        `needed` bytes of shm become reclaimable; dropping the pin buffer
        makes the shm copy LRU-evictable while the disk file keeps the
        object alive. Runs in executor threads — mutations use atomic
        dict ops only."""
        freed = 0
        for oid, buf in list(self._pinned.items()):
            if freed >= needed:
                break
            if oid not in self._spilled:
                os.makedirs(self._spill_dir, exist_ok=True)
                path = os.path.join(self._spill_dir, oid.hex())
                with open(path, "wb") as f:
                    f.write(buf)
                self._spilled[oid] = (path, buf.nbytes)
            freed += buf.nbytes
            self._pinned.pop(oid, None)  # buffer release -> evictable
        if freed:
            logger.info("spilled %d bytes to %s", freed, self._spill_dir)
        return freed

    async def _restore_async(self, oid_bytes: bytes) -> bool:
        if oid_bytes not in self._spilled:
            return False
        async with self._spill_lock:
            return await asyncio.get_event_loop().run_in_executor(
                None, self._restore_spilled, oid_bytes)

    def _restore_spilled(self, oid_bytes: bytes) -> bool:
        """Load a spilled object back into shm, reading straight into
        the store buffer (no whole-object intermediate copy — the node
        is memory-pressured by definition when this runs). The disk file
        stays authoritative until the owner unpins."""
        rec = self._spilled.get(oid_bytes)
        if rec is None:
            return False
        path, size = rec
        oid = ObjectID(oid_bytes)
        if self.store.contains(oid):
            return True
        try:
            with open(path, "rb") as f:
                buf = self._create_with_spill(oid, size)
                f.readinto(buf)
        except OSError:
            self._spilled.pop(oid_bytes, None)
            return False
        self.store.seal(oid)
        self.store.release(oid)
        return True

    # -- primary-copy pinning (reference: local_object_manager.h — the
    # raylet holding an owned object's primary copy keeps it unevictable
    # until the owner releases it) -------------------------------------

    async def rpc_pin_object(self, req):
        oid = ObjectID(req["object_id"])
        if req["object_id"] in self._pinned:
            return {"ok": True}
        if req["object_id"] in self._spilled:
            return {"ok": True}  # the disk file is the pinned copy
        # timeout=-1 is the NON-BLOCKING probe (0 means wait-forever and
        # would wedge the raylet's event loop on an evicted object)
        buf = self.store.get_buffer(oid, timeout=-1)
        if buf is None:
            if await self._restore_async(req["object_id"]):
                buf = self.store.get_buffer(oid, timeout=-1)
        if buf is None:
            return {"ok": False, "error": "object not in store"}
        # holding the buffer holds the store refcount; LRU only evicts
        # refcount-zero objects
        self._pinned[req["object_id"]] = buf
        # primary-copy hint in the slot itself: loss sweeps and the
        # drop_objects chaos fault can tell authoritative copies from
        # pulled replicas without consulting this process's dicts
        self.store.set_primary(oid, True)
        return {"ok": True}

    async def rpc_unpin_object(self, req):
        oid = req["object_id"]
        buf = self._pinned.pop(oid, None)
        rec = self._spilled.pop(oid, None)
        if rec is not None:
            try:
                os.unlink(rec[0])
            except OSError:
                pass
        if req.get("free"):
            # the owner's distributed refcount hit zero: delete the shm
            # copy outright instead of waiting for eviction pressure.
            # Drop OUR buffer reference first, then only force-delete a
            # refcount-zero slot — yanking a slot while a reader still
            # maps it would corrupt zero-copy views.
            del buf
            object_id = ObjectID(oid)
            if self.store.refcount(object_id) == 0:
                self.store.delete(object_id)
                self._objects_freed += 1
        return {"ok": True}

    def _chaos_drop_objects(self, frac: float, rng) -> int:
        """Timed-fault target (fault_injection `drop_objects[:<frac>]`):
        force-delete a seeded random subset of this node's sealed
        objects, pins included, WITHOUT killing the process — models
        silent object loss (arena corruption, operator fat-finger) as
        distinct from whole-node death. Runs on the chaos timer thread;
        dict ops are GIL-atomic and the store delete is shard-locked."""
        rows = self.store.list_sealed()
        if not rows:
            return 0
        k = max(1, int(len(rows) * frac))
        chosen = rng.sample(rows, min(k, len(rows)))
        dropped = 0
        for oid, _primary, _referenced in chosen:
            key = oid.binary()
            # drop our pin's buffer reference first — the point is to
            # lose primary copies, and a pinned slot is refcounted
            self._pinned.pop(key, None)
            if self.store.refcount(oid) > 0:
                continue  # a live reader maps the slot: yanking it
                # would corrupt a zero-copy view, not simulate loss
            self.store.delete(oid)
            dropped += 1
        self._objects_dropped += dropped
        return dropped

    async def rpc_spill_objects(self, req):
        """A local worker's plasma create failed: make room by spilling
        pinned primary copies to disk (reference: the raylet triggering
        spill on CreateRequestQueue pressure)."""
        async with self._spill_lock:
            freed = await asyncio.get_event_loop().run_in_executor(
                None, self._spill_up_to, req["needed"])
        return {"freed": freed}

    async def rpc_metrics_text(self, req):
        """Prometheus text over RPC (same rationale as the GCS twin)."""
        return {"text": self._metrics_text()}

    async def rpc_dump_stacks(self, req):
        """This raylet's Python thread stacks, optionally fanned out to
        every registered worker on the node (`req['workers']`) — one
        node's contribution to `ray_tpu stack --all`. Workers answer on
        their core-worker RPC loop, which lives on its own thread, so a
        worker whose MAIN thread is wedged still reports the stack that
        proves it; a worker that can't answer at all contributes an
        error row instead of stalling the aggregate (bounded timeout)."""
        out = {"pid": os.getpid(), "role": "raylet",
               "node_id": self.node_id.binary().hex(),
               "threads": health_mod.dump_stacks()}
        if req.get("workers"):
            timeout = float(req.get("timeout", 5.0))
            rows = []
            for w in list(self._workers.values()):
                if not w.alive:
                    continue
                try:
                    client = await self.clients.get(w.addr)
                    r = await client.call("dump_stacks", {},
                                          timeout=timeout)
                    rows.append(r)
                except (ConnectionLost, RpcError, OSError,
                        asyncio.TimeoutError) as e:
                    rows.append({"pid": w.pid, "role": "worker",
                                 "error": f"{type(e).__name__}: {e}"})
            out["workers"] = rows
        return out

    async def rpc_get_store_stats(self, req):
        return self.store.stats()

    async def rpc_list_objects(self, req):
        """Primary copies this raylet is responsible for: pinned (shm)
        and spilled (disk) objects, for the state API."""
        out = []
        for oid, buf in self._pinned.items():
            out.append({"object_id": oid.hex(), "where": "shm",
                        "size": buf.nbytes})
        for oid, (path, size) in self._spilled.items():
            out.append({"object_id": oid.hex(), "where": "spilled",
                        "size": size, "path": path})
        return out

    async def rpc_node_info(self, req):
        return {
            "node_id": self.node_id.binary(),
            "store_name": self.store_name,
            "total": self.total,
            "available": self.available,
            "num_workers": len(self._workers),
        }


async def main(args):
    _fi.set_role("raylet")  # arm raylet-scoped timed faults
    resources = json.loads(args.resources) if args.resources else None
    raylet = Raylet(
        gcs_addr=args.gcs_addr,
        host=args.host,
        port=args.port,
        resources=resources,
        store_name=args.store_name or None,
        object_store_memory=args.object_store_memory or None,
        session_dir=args.session_dir,
        labels=json.loads(args.labels) if args.labels else None,
    )
    await raylet.start(metrics_port=args.metrics_port)
    print(f"RAYLET_READY {raylet.address} {raylet.store_name} "
          f"{raylet.node_id.hex()}", flush=True)
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    loop.add_signal_handler(signal.SIGTERM, stop.set)
    loop.add_signal_handler(signal.SIGINT, stop.set)

    async def parent_watch():
        # Daemons are children of the driver that spawned the cluster; if
        # that driver dies abruptly (crash, SIGKILL) we are reparented to
        # init — tear down instead of leaking (reference: raylets die with
        # the session via `ray stop`; subreaper kills orphans).
        parent = os.getppid()
        while os.getppid() == parent:
            await asyncio.sleep(1.0)
        stop.set()

    if not getattr(args, 'daemonize', False):
        asyncio.ensure_future(parent_watch())
    await stop.wait()
    # Graceful teardown: kill worker children, unlink the shm arena.
    await raylet.stop()


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-addr", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default=None)
    parser.add_argument("--store-name", default=None)
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    parser.add_argument("--labels", default=None,
                        help="JSON node labels (slice membership)")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="serve Prometheus /metrics on this port")
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--daemonize", action="store_true",
                        help="survive the launching process (CLI mode)")
    args = parser.parse_args()
    if args.log_file:
        logging.basicConfig(filename=args.log_file, level=logging.INFO)
    asyncio.run(main(args))
