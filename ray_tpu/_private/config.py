"""Central config registry, env-var overridable.

Equivalent of the reference's `RayConfig` macro registry
(`src/ray/common/ray_config_def.h` — 216 `RAY_CONFIG(...)` knobs, each
overridable via a `RAY_<name>` env var). Here every knob is declared once with
a type and default and can be overridden with `RAY_TPU_<NAME>` env vars or
programmatically via `ray_tpu.init(_system_config={...})`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class Config:
    # --- object store / data plane ---
    # Objects <= this many bytes are returned in-band in the task reply and
    # live in the owner's in-process memory store (reference:
    # `max_direct_call_object_size`, ray_config_def.h:206 — 100KB default).
    max_direct_call_object_size: int = 100 * 1024
    # Streaming generators: how many reported-but-unconsumed items the
    # owner buffers before it withholds the executor's ack (reference:
    # generator_waiter.h backpressure threshold).
    streaming_backpressure_items: int = 16
    # Node-to-node object transfer chunk size (reference:
    # object_manager_default_chunk_size, ray_config_def.h) and how many
    # chunk fetches ride in flight per object.
    object_transfer_chunk_bytes: int = 4 * 1024 * 1024
    object_transfer_parallelism: int = 4
    # Outstanding worker-lease requests per scheduling key (reference:
    # max_pending_lease_requests_per_scheduling_category).
    max_lease_requests_per_key: int = 8
    # Tasks pushed to one leased worker before its first reply arrives
    # (reference: max_tasks_in_flight_per_worker,
    # direct_task_transport.h:75 lease pipelining). The worker queues
    # them FIFO; pipelining amortizes the submit round trip for small
    # tasks.
    max_tasks_in_flight_per_worker: int = 16
    # Default per-node shared-memory store capacity.
    object_store_memory: int = 2 * 1024**3
    # Object-table slots in the shm store header.
    object_store_table_size: int = 65536
    # Chunk size for node-to-node object transfer.
    object_transfer_chunk_bytes: int = 8 * 1024**2

    # --- scheduling ---
    # Hybrid policy: pack onto the local node until its utilization crosses
    # this threshold, then spread (reference hybrid_scheduling_policy).
    scheduler_spread_threshold: float = 0.5
    # How long a leased worker is kept by a submitter with no queued tasks.
    idle_lease_keepalive_s: float = 0.2
    # Max workers a raylet will fork per node by default: num_cpus.
    maximum_startup_concurrency: int = 8
    # consecutive pre-registration worker deaths for one pool key before
    # the raylet stops respawning and fails the waiting leases (a broken
    # runtime-env interpreter would otherwise crash-loop forever)
    max_worker_startup_failures: int = 5
    # Worker pool: keep this many idle workers warm.
    num_prestart_workers: int = 0
    worker_register_timeout_s: float = 30.0

    # --- host memory monitor (reference: memory_monitor.h:52 +
    # worker_killing_policy_group_by_owner.h) ---
    # Kill workers when host used/total crosses this fraction; <= 0
    # disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250
    # Test hook: a file containing "used_bytes total_bytes" read instead
    # of /proc/meminfo (empty = real host memory).
    memory_usage_path: str = ""

    # --- health / fault tolerance ---
    raylet_heartbeat_period_s: float = 0.5
    health_check_failure_threshold: int = 10
    actor_max_restarts_default: int = 0
    task_max_retries_default: int = 3
    # Lineage: max bytes of task specs retained by an owner for reconstruction.
    # Also settable as RAY_TPU_LINEAGE_MAX_BYTES (alias).
    max_lineage_bytes: int = 1024**3
    # Deepest chain of missing upstream inputs a single reconstruction
    # will recursively re-submit before giving up with ObjectLostError
    # (reference: lineage depth bound in task_manager resubmit).
    lineage_max_depth: int = 100
    # Per producing task: how many times its lost returns may be
    # re-executed before the owner marks them unreconstructable
    # (reference: max_retries semantics on object recovery).
    max_object_reconstructions: int = 3

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_max_frame_bytes: int = 512 * 1024**2
    # Write-side frame coalescing: logical messages queued within one
    # event-loop tick share a BATCH wire frame; crossing either watermark
    # flushes immediately. 1 disables batching (every message is its own
    # frame, byte-identical to the pre-BATCH wire format).
    rpc_batch_max_msgs: int = 128
    rpc_batch_max_bytes: int = 256 * 1024
    # Transport send-buffer high-watermark: above this the coalescer stops
    # writing and parks behind one awaited drain() (backpressure for the
    # call_nowait pipelined path against a slow peer).
    rpc_send_high_watermark: int = 4 * 1024**2

    # --- gcs ---
    gcs_pubsub_batch_ms: float = 5.0
    resource_broadcast_period_s: float = 0.1

    # --- paths ---
    session_dir_root: str = "/tmp/ray_tpu"

    def update(self, overrides: dict[str, Any] | None = None) -> "Config":
        if overrides:
            for key, value in overrides.items():
                if not hasattr(self, key):
                    raise ValueError(f"Unknown config key: {key}")
                setattr(self, key, value)
        return self

    # Alternate env spellings: RAY_TPU_<alias> -> field. The canonical
    # RAY_TPU_<FIELD_NAME> form always works; aliases exist where the
    # documented knob name differs from the field (wins over the
    # canonical spelling when both are set).
    _ENV_ALIASES = {
        "LINEAGE_MAX_BYTES": "max_lineage_bytes",
        "LINEAGE_MAX_DEPTH": "lineage_max_depth",
    }

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        alias_for = {v: k for k, v in cls._ENV_ALIASES.items()}
        for f in fields(cls):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            alias = alias_for.get(f.name)
            if alias is not None:
                env = os.environ.get(_ENV_PREFIX + alias, env)
            if env is not None:
                if f.type in ("int", int):
                    setattr(cfg, f.name, int(env))
                elif f.type in ("float", float):
                    setattr(cfg, f.name, float(env))
                elif f.type in ("bool", bool):
                    setattr(cfg, f.name, env.lower() in ("1", "true", "yes"))
                else:
                    setattr(cfg, f.name, env)
        return cfg


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def set_global_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
