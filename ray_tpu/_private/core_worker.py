"""CoreWorker — the per-process runtime linked into every driver and worker.

Reference: `src/ray/core_worker/core_worker.h:292` and its transport layer —
task submission with cached worker leases
(`CoreWorkerDirectTaskSubmitter`, `transport/direct_task_transport.h:75`),
direct actor transport with per-caller sequence numbers
(`CoreWorkerDirectActorTaskSubmitter`), the in-process memory store for
small/in-band objects (`store_provider/memory_store/memory_store.h:43`),
ownership bookkeeping (`reference_count.h`), task retries (`task_manager.h`),
and the task-execution callback into user code (`_raylet.pyx execute_task`).

Threading model: all network state lives on a dedicated asyncio loop thread
(the reference's io_service); the public sync API posts coroutines to it.
Task execution happens on the process main thread (normal tasks), a thread
pool (threaded actors), or a dedicated actor event loop (async actors).
"""

from __future__ import annotations

import asyncio
import contextvars
import hashlib
import inspect
import itertools
import logging
import os
import queue as queue_mod
import threading
import time
import traceback
from collections import OrderedDict, deque
from concurrent.futures import Future as SyncFuture
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as SyncTimeoutError
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import serialization
from ray_tpu._private import task as task_mod
from ray_tpu._private.config import Config
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef, set_core_worker
from ray_tpu._private.object_store import ObjectStore
from ray_tpu.util import tracing
from ray_tpu._private.rpc import (
    ClientPool,
    ConnectionLost,
    ReconnectingClient,
    RpcError,
    RpcServer,
)

logger = logging.getLogger(__name__)


class RayTaskError(Exception):
    """A task raised; carries the remote traceback (reference:
    ray.exceptions.RayTaskError)."""

    def __init__(self, message: str, cause: Exception | None = None):
        super().__init__(message)
        self.cause = cause


# The task id executing on THIS thread/coroutine. A ContextVar is the
# one mechanism correct for BOTH executor shapes: pool threads each see
# their own context, and every asyncio task gets a copied context — so
# concurrent async actor tasks attribute their children correctly where
# a shared instance attribute could not (recursive-cancel bookkeeping).
_executing_task_id: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_executing_task_id", default=None)


class TaskCancelledError(RayTaskError):
    """The task was cancelled via ray_tpu.cancel() (reference:
    `ray.exceptions.TaskCancelledError`; cancel protocol
    `src/ray/protobuf/core_worker.proto:252-270`)."""


class _TaskCancelledInterrupt(BaseException):
    """Raised asynchronously inside an executing worker thread to
    interrupt a running task (the reference interrupts with
    KeyboardInterrupt — a BaseException so `except Exception` in user
    code cannot swallow the cancellation)."""


class ActorDiedError(RayTaskError):
    pass


class OutOfMemoryError(RayTaskError):
    """The raylet's memory monitor killed the worker running this task
    (reference: ray.exceptions.OutOfMemoryError); the message carries the
    killing policy's reasoning."""


class GetTimeoutError(Exception):
    pass


class ObjectLostError(RayTaskError):
    """Every copy of an object is gone and it cannot be reconstructed
    (reference: ray.exceptions.ObjectLostError). The message names the
    lost object and, when known, the lineage that died with it — a get()
    on such an object fails NOW instead of blocking to its timeout."""


class _MemoryStore:
    """In-process store for in-band results + object status (owner side)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self.values: Dict[bytes, bytes] = {}       # oid -> value frame
        self.errors: Dict[bytes, bytes] = {}       # oid -> pickled-exc frame
        self.locations: Dict[bytes, List[str]] = {}  # oid -> raylet addrs
        self._events: Dict[bytes, asyncio.Event] = {}
        # Global completion pulse: set on every _signal. `wait` scans +
        # blocks on this instead of growing a watcher future per pending
        # ref per call (which is O(n^2) across a drain loop).
        self._any_event = asyncio.Event()
        # Serializes sentinel→Future upgrades across getter threads
        # (cold path: taken only when a thread is about to block).
        self._arm_lock = threading.Lock()
        # Caller-thread waiters. At submit time each pending return is
        # registered with a None sentinel (a dict store — creating a
        # concurrent Future with its Condition per call would dominate
        # the submit path); `_get_fast` swaps in a real SyncFuture only
        # when a thread actually blocks. The reply handler (loop thread)
        # pops the entry and resolves it if it grew a Future.
        self.thread_waiters: Dict[bytes, Optional[SyncFuture]] = {}

    def _event(self, oid: bytes) -> asyncio.Event:
        ev = self._events.get(oid)
        if ev is None:
            ev = asyncio.Event()
            self._events[oid] = ev
        return ev

    def ready(self, oid: bytes) -> bool:
        return oid in self.values or oid in self.errors or oid in self.locations

    def register_thread_waiter(self, oid: bytes) -> None:
        """Mark oid as a pending owned result (cheap sentinel form)."""
        # Sentinel store from the single submit thread before any getter
        # can observe the oid — part of the documented lock-free protocol
        # above (only the upgrade path needs _arm_lock).
        self.thread_waiters[oid] = None  # raylint: disable=lock-discipline

    def arm_thread_waiter(self, oid: bytes) -> Optional[SyncFuture]:
        """Caller-thread: upgrade the sentinel to a blockable Future.
        Returns None if the result is no longer pending (the caller must
        re-check the value dicts)."""
        with self._arm_lock:  # two getter threads must SHARE one future
            if oid not in self.thread_waiters:
                return None
            existing = self.thread_waiters[oid]
            if existing is not None:
                # already armed by another thread — replacing it would
                # strand that thread forever (_signal resolves only the
                # stored one). If the reply just landed and resolved it,
                # result() returns immediately anyway.
                return existing
            fut = SyncFuture()
            self.thread_waiters[oid] = fut
        # Re-check AFTER publishing: if the reply landed between the
        # membership test and the store (the loop thread pops without
        # the lock), the value dicts are already populated and the
        # orphaned entry must not linger. RESOLVE what we pop — another
        # thread may have grabbed this same future in the meantime and
        # would otherwise block on it forever.
        if self.ready(oid):
            # loop-thread-style pop, deliberately outside _arm_lock (see
            # ordering comment above) # raylint: disable=lock-discipline
            w = self.thread_waiters.pop(oid, None)
            if w is not None and not w.done():
                w.set_result(True)
            return None
        return fut

    def _signal(self, oid: bytes):
        ev = self._events.pop(oid, None)
        if ev is not None:
            ev.set()
        # loop thread is the sole popper; armed futures are resolved, not
        # mutated, so no lock is needed # raylint: disable=lock-discipline
        waiter = self.thread_waiters.pop(oid, None)
        if waiter is not None and not waiter.done():
            waiter.set_result(True)
        self._any_event.set()

    async def wait_any(self, timeout: float | None):
        """Loop-thread: block until ANY object completes (or timeout).
        The caller must scan for readiness BEFORE calling (same loop
        iteration — signals only fire on the loop thread, so no signal
        can slip between the scan and the clear here)."""
        self._any_event.clear()
        await asyncio.wait_for(self._any_event.wait(), timeout)

    def put_value(self, oid: bytes, frame: bytes):
        self.values[oid] = frame
        self._signal(oid)

    def put_error(self, oid: bytes, frame: bytes):
        self.errors[oid] = frame
        self._signal(oid)

    def add_location(self, oid: bytes, raylet_addr: str):
        self.locations.setdefault(oid, [])
        if raylet_addr not in self.locations[oid]:
            self.locations[oid].append(raylet_addr)
        self._signal(oid)

    def drop_location(self, oid: bytes, raylet_addr: str):
        """Remove a dead/stale location; when the last one goes, the
        object is 'not ready' again so status waiters block until a
        reconstruction (or late report) re-adds one."""
        locs = self.locations.get(oid)
        if locs is None:
            return
        if raylet_addr in locs:
            locs.remove(raylet_addr)
        if not locs:
            self.locations.pop(oid, None)
            ev = self._events.get(oid)
            if ev is not None and oid not in self.values \
                    and oid not in self.errors:
                ev.clear()

    async def wait_ready(self, oid: bytes, timeout: float | None = None):
        if self.ready(oid):
            return
        await asyncio.wait_for(self._event(oid).wait(), timeout)


class ObjectRefGenerator:
    """Iterator over a streaming task's item refs (reference:
    `_raylet.pyx:273` ObjectRefGenerator). Yields ObjectRefs as the
    executor produces them; `ray_tpu.get` each ref for its value.
    `close()` cancels the producer at its next report."""

    def __init__(self, core_worker: "CoreWorker", task_id: bytes):
        self._cw = core_worker
        self._task_id = task_id
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._cw.stream_next(self._task_id)

    def next_with_timeout(self, timeout: float) -> ObjectRef:
        return self._cw.stream_next(self._task_id, timeout)

    async def _anext_async(self) -> ObjectRef:
        """Owner-loop async variant (internal plumbing for Serve/Data)."""
        out = await self._cw._stream_next_async(self._task_id)
        if out is type(self._cw)._STREAM_DONE:
            raise StopAsyncIteration
        return out

    def close(self):
        if not self._closed:
            self._closed = True
            self._cw.stream_cancel(self._task_id)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class _KeyState:
    """Per-scheduling-key submit queue + lease pipeline state."""

    __slots__ = ("queue", "requesting")

    def __init__(self):
        self.queue: deque = deque()
        self.requesting = 0


class CoreWorker:
    def __init__(
        self,
        mode: str,  # "driver" | "worker"
        gcs_addr: str,
        raylet_addr: str | None = None,
        job_id: JobID | None = None,
        store: ObjectStore | None = None,
        node_id_hex: str = "",
        config: Config | None = None,
        tpu_chips: tuple = (),
    ):
        self.mode = mode
        self.config = config or Config.from_env()
        self.worker_id = WorkerID.from_random()
        # Never default to a shared job 0: an unlabelled CoreWorker gets
        # its own bucket so per-job accounting (fair queue lanes, store
        # quotas) can't silently merge tenants.
        self.job_id = job_id if job_id is not None else JobID.from_random()
        self.gcs_addr = gcs_addr
        self.raylet_addr = raylet_addr
        self.node_id_hex = node_id_hex
        self.store = store
        if store is not None:
            # stamp this process's puts with its job for per-job byte
            # accounting in the shm store (drivers and workers alike)
            store.set_current_job(self.job_id.binary())
            # quota_flood@<role> chaos victimizer: one job-charged put
            # per call, QuotaExceededError propagating to the flood
            # loop's rejection counter
            _fi.set_quota_flood_target(
                lambda: store.put_value(ObjectID.from_random(),
                                        b"\x00" * 65536))
        self.tpu_chips = tpu_chips
        # Per-PROCESS random base task id, NOT a job-deterministic one:
        # submissions from non-task threads (driver main thread, worker
        # background threads like Data's split coordinator) use this as
        # the parent. A shared deterministic base would give two
        # processes identical (parent, counter) pairs — colliding task
        # and return-object ids that alias stale values across owners.
        self.current_task_id = TaskID.from_random()
        self.current_actor_id: Optional[ActorID] = None

        self._put_counter = itertools.count(1)
        self._task_counter = itertools.count(1)
        self._seq_counters: Dict[bytes, itertools.count] = {}

        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="ray_tpu-io", daemon=True
        )
        self._server = RpcServer()
        self._clients = ClientPool()
        self._key_states: Dict[tuple, _KeyState] = {}
        self._actor_clients: Dict[bytes, dict] = {}  # actor state cache
        self._actor_events: Dict[bytes, asyncio.Event] = {}
        # --- ownership plane (reference: reference_count.h) ---
        # _ref_lock is REENTRANT: ObjectRef.__del__ fires via the cycle
        # collector during any allocation — including while this same
        # thread already holds the lock — and deregister_ref must not
        # deadlock against ourselves. Discipline: mutate and decide
        # under the lock, act (RPC, enqueue) outside it.
        self._ref_lock = threading.RLock()
        self._local_refs: Dict[bytes, int] = {}
        # oid -> in-flight submitted tasks carrying the oid as an arg: a
        # caller that drops its handle right after `.remote()` must not
        # free an object the task still needs.
        self._task_arg_refs: Dict[bytes, int] = {}
        # Owner side: oid -> worker addresses that borrowed the ref
        # (deserialized it inside a task they execute). The object stays
        # alive until every borrower reports release (the reference's
        # WaitForRefRemoved protocol, inverted to borrower-push).
        self._borrowers: Dict[bytes, set] = {}
        # Borrower side: oid -> owner addr for refs this process holds
        # but does not own; the last local deref notifies the owner.
        self._borrowed_refs: Dict[bytes, str] = {}
        # Return-value handoffs: return oid -> [(nested oid, owner)]
        # for ObjectRefs pickled inside a task's return. Each pair
        # holds a _task_arg_refs count until the RETURN object itself
        # is released — the serialized reply "contains" the ref, so it
        # must keep the object alive even if this process never
        # deserializes a handle.
        self._contained_refs: Dict[bytes, List[tuple]] = {}
        # Producing task id -> reconstruction attempts consumed
        # (bounded by config.max_object_reconstructions).
        self._reconstruction_attempts: Dict[bytes, int] = {}
        # Oids whose lineage was evicted past max_lineage_bytes: a loss
        # is then permanent and the ObjectLostError should say why.
        self._lineage_evicted: set = set()
        # Owned plasma objects freed on refcount zero; consulted so a
        # late borrower status query errors instead of hanging.
        self._freed_objects: set = set()
        # recovery-plane counters (exported via the "ownership" metrics
        # callback; loop-thread writes, so plain ints suffice)
        self._stats_reconstructions = 0
        self._stats_reconstruction_failures = 0
        self._stats_reconstruction_depth_max = 0
        self._stats_lineage_evictions = 0
        self._stats_objects_freed = 0
        self._stats_borrower_notifies = 0
        # Owner-side streaming-generator state, keyed by the producing
        # task id (reference: StreamingGeneratorState in task_manager.h).
        self._streams: Dict[bytes, dict] = {}
        # Lineage (reference: TaskManager lineage pinning,
        # task_manager.h:208,269): specs of tasks whose returns live in
        # plasma, retained so a lost object can be re-executed. Bounded
        # by config.max_lineage_bytes, evicting oldest-first.
        self._lineage: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._lineage_oids: Dict[bytes, bytes] = {}  # oid -> task_id
        # put()-path pins in flight: oid -> future resolved at pin ack
        # (consulted by _unpin_at to preserve pin-before-unpin order)
        self._pending_pins: Dict[bytes, asyncio.Future] = {}
        self._lineage_bytes = 0
        self._reconstructing: Dict[bytes, asyncio.Future] = {}
        # Primary-copy pins (reference: local_object_manager pinning —
        # the raylet holding an owned object's primary copy keeps it
        # unevictable until the owner's refcount drops to zero).
        self._pinned_at: Dict[bytes, str] = {}
        # Task-event buffer (reference: TaskEventBuffer,
        # task_event_buffer.h — batched, periodically flushed to the
        # GCS task table for `list tasks` observability).
        self._task_events: List[dict] = []
        # Submission coalescing: caller threads append specs here and
        # schedule ONE loop callback per burst instead of one per task —
        # the flush groups actor tasks into batched push frames
        # (reference: the submit queue in direct_task_transport.h).
        self._submit_buffer: deque = deque()  # ("normal"|"actor", spec)
        self._submit_flush_scheduled = False
        # Cancellation (reference: CancelTask/RemoteCancelTask,
        # core_worker.proto:252-270). Owner side: ids the user cancelled
        # (suppresses retries; pending specs error out at push time) and
        # where each in-flight task was pushed (to route the cancel RPC).
        # id -> insertion time: entries are dropped at terminal reply
        # AND age-pruned (a cancel of an already-finished task would
        # otherwise park its id here forever).
        self._cancelled_tasks: Dict[bytes, float] = {}
        self._inflight_tasks: Dict[bytes, str] = {}  # task_id -> addr

        # Executor state (worker mode). SimpleQueue: C-implemented
        # lock-free handoff — the per-task wakeup is measurably cheaper
        # than queue.Queue's pure-Python condition variables.
        self._exec_queue: queue_mod.SimpleQueue = queue_mod.SimpleQueue()
        self._actor_instance = None
        self._actor_threadpool: Optional[ThreadPoolExecutor] = None
        self._actor_group_pools: Optional[Dict[str, ThreadPoolExecutor]] = None
        self._actor_group_sems: Dict[str, Any] = {}
        self._actor_async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._actor_seq_state: Dict[bytes, dict] = {}
        self._function_cache: Dict[bytes, Any] = {}
        # Executor side of cancellation: ids whose cancel arrived before
        # (or during) execution; running task -> thread ident (sync) or
        # asyncio.Task (async actors); executing task -> ids of the
        # child tasks it submitted (recursive cancel). Same id -> time
        # age-pruned form as _cancelled_tasks.
        self._cancel_requested: Dict[bytes, float] = {}
        self._running_threads: Dict[bytes, int] = {}
        self._running_async: Dict[bytes, Any] = {}
        self._task_children: Dict[bytes, List[bytes]] = {}
        self._shutdown = False
        self.memory_store: Optional[_MemoryStore] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_forever()

    def start(self):
        self._loop_thread.start()
        self._run_sync(self._start_async())
        set_core_worker(self)
        try:
            from ray_tpu.util.metrics import DEFAULT_REGISTRY
            DEFAULT_REGISTRY.register_callback(
                "ownership", self._ownership_metrics_text)
        except Exception:  # noqa: BLE001 — observability only
            pass
        return self

    def _ownership_metrics_text(self) -> str:
        """Ownership/recovery plane for /metrics (keyed callback — one
        CoreWorker per process, re-registration replaces)."""
        if self.memory_store is None:
            return ""
        with self._ref_lock:
            owned = len(self._local_refs)
            borrowed = len(self._borrowed_refs)
            task_args = len(self._task_arg_refs)
            borrower_edges = sum(
                len(v) for v in self._borrowers.values())
        rows = [
            ("ray_tpu_owned_refs", "gauge", owned),
            ("ray_tpu_borrowed_refs", "gauge", borrowed),
            ("ray_tpu_task_arg_refs", "gauge", task_args),
            ("ray_tpu_borrower_edges", "gauge", borrower_edges),
            ("ray_tpu_lineage_bytes", "gauge", self._lineage_bytes),
            ("ray_tpu_lineage_tasks", "gauge", len(self._lineage)),
            ("ray_tpu_lineage_evictions_total", "counter",
             self._stats_lineage_evictions),
            ("ray_tpu_reconstructions_total", "counter",
             self._stats_reconstructions),
            ("ray_tpu_reconstruction_failures_total", "counter",
             self._stats_reconstruction_failures),
            ("ray_tpu_reconstruction_depth_max", "gauge",
             self._stats_reconstruction_depth_max),
            ("ray_tpu_objects_freed_total", "counter",
             self._stats_objects_freed),
            ("ray_tpu_borrower_notifies_total", "counter",
             self._stats_borrower_notifies),
        ]
        out = []
        for name, kind, value in rows:
            out.append(f"# TYPE {name} {kind}")
            out.append(f"{name} {value}")
        return "\n".join(out) + "\n"

    async def _start_async(self):
        self.memory_store = _MemoryStore(self._loop)
        self._server.register_all(self)
        await self._server.start()
        # reconnecting handle: survives a GCS restart (persistence FT)
        self.gcs = ReconnectingClient(self._clients, self.gcs_addr)
        await self.gcs.call("subscribe",
                            {"channel": "actors", "addr": self._server.address})
        # node-death notices drive owner-side location invalidation and
        # lineage reconstruction (drivers AND workers own objects)
        await self.gcs.call("subscribe",
                            {"channel": "nodes", "addr": self._server.address})
        self._event_flush_task = asyncio.ensure_future(
            self._event_flush_loop())

    def _emit_task_event(self, task_id: bytes, name: str,
                         task_type: str, state: str):
        # tuple form: 2 emits per task ride the submit/reply hot paths,
        # and a 5-tuple packs ~3x cheaper than a 5-key string map
        self._task_events.append((task_id, name, task_type, state,
                                  time.time()))

    async def _event_flush_loop(self):
        """Ship buffered task events to the GCS task table ~1/s
        (reference: TaskEventBuffer's periodic flush; fire-and-forget so
        observability never sits on the task path)."""
        while not self._shutdown:
            await asyncio.sleep(1.0)
            # drain the WHOLE buffer each tick (in bounded frames) — a
            # fixed drain rate below the emit rate would grow the buffer
            # without bound
            while self._task_events:
                batch, self._task_events = self._task_events[:512], \
                    self._task_events[512:]
                try:
                    await self.gcs.notify("add_task_events",
                                          {"events": batch})
                except (ConnectionLost, RpcError, OSError):
                    break

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._run_sync(self._stop_async(), timeout=5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        set_core_worker(None)

    async def _stop_async(self):
        task = getattr(self, "_event_flush_task", None)
        if task is not None:
            task.cancel()  # mid-sleep; the tail flush below covers it
        if self._task_events:
            # a short-lived driver exits before the periodic flush —
            # ship the tail so its tasks appear in `list tasks`
            batch, self._task_events = self._task_events, []
            try:
                await self.gcs.notify("add_task_events",
                                      {"events": batch})
            except (ConnectionLost, RpcError, OSError):
                pass
        await self._clients.close_all()
        await self._server.stop()

    @property
    def address(self) -> str:
        return self._server.address

    def _run_sync(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    # ------------------------------------------------------------------
    # reference registry (local refcounts; reference: reference_count.h)
    # ------------------------------------------------------------------

    def _ref_gone(self, oid: bytes) -> bool:
        """Owner side, caller holds _ref_lock: nothing keeps oid alive —
        no local handle, no in-flight task argument, no borrower."""
        return (self._local_refs.get(oid, 0) <= 0
                and self._task_arg_refs.get(oid, 0) <= 0
                and not self._borrowers.get(oid))

    def register_ref(self, ref: ObjectRef):
        oid = ref.binary()
        borrow_from = None
        with self._ref_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1
            if (ref.owner_addr not in ("", self.address)
                    and oid not in self._borrowed_refs):
                # first handle to a ref this process does not own:
                # record the borrow and tell the owner, which keeps the
                # object alive until we report release. (The notify is
                # async; the submitted-task ref the owner holds until
                # our task's terminal reply covers the in-flight gap.)
                self._borrowed_refs[oid] = ref.owner_addr
                borrow_from = ref.owner_addr
        if borrow_from is not None and not self._shutdown:
            try:
                self._submit_enqueue("add_borrower", (oid, borrow_from))
            except RuntimeError:
                pass  # loop already closed at interpreter teardown

    def deregister_ref(self, ref: ObjectRef):
        oid = ref.binary()
        action = None  # decided under the lock, performed outside it
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
            owner = self._borrowed_refs.get(oid)
            if owner is not None:
                # borrower side: release the borrow only when no
                # submitted task of OURS still carries the ref either
                if self._task_arg_refs.get(oid, 0) <= 0:
                    self._borrowed_refs.pop(oid, None)
                    action = ("remove_borrower", (oid, owner))
            elif self._ref_gone(oid):
                # owner side: last holder gone. Posted unconditionally —
                # the reply that records the pin may still be in flight
                # on the loop thread, so gating on "is a pin recorded
                # yet" here would race it (the reply side re-checks the
                # refcount after recording to cover the other order).
                action = ("release", oid)
        if action is not None and not self._shutdown:
            try:
                # rides the submit buffer: a release between two
                # `.remote()` calls shares their loop wakeup instead
                # of paying its own
                self._submit_enqueue(*action)
            except RuntimeError:
                pass  # loop already closed at interpreter teardown

    def _retain_args(self, spec: task_mod.TaskSpec):
        """Pin every by-reference argument for the submitted task's
        lifetime (reference: the submitted-task reference count in
        reference_count.h). Released at the terminal reply/error."""
        deps = spec.plasma_deps()
        if not deps:
            return
        with self._ref_lock:
            for oid, _owner in deps:
                self._task_arg_refs[oid] = \
                    self._task_arg_refs.get(oid, 0) + 1

    def _release_args(self, spec: task_mod.TaskSpec):
        """Terminal reply/error (loop thread): drop the submitted-task
        pins taken by _retain_args and free whatever hit zero."""
        deps = spec.plasma_deps()
        if not deps:
            return
        actions = []
        with self._ref_lock:
            for oid, _owner in deps:
                n = self._task_arg_refs.get(oid, 0) - 1
                if n > 0:
                    self._task_arg_refs[oid] = n
                    continue
                self._task_arg_refs.pop(oid, None)
                owner = self._borrowed_refs.get(oid)
                if owner is not None:
                    if self._local_refs.get(oid, 0) <= 0:
                        self._borrowed_refs.pop(oid, None)
                        actions.append(("remove_borrower", (oid, owner)))
                elif self._ref_gone(oid):
                    actions.append(("release", oid))
        for kind, payload in actions:
            if kind == "release":
                self._on_ref_released(payload)
            else:
                asyncio.ensure_future(
                    self._notify_borrow(payload[1], "remove_borrower",
                                        payload[0]))

    def _release_contained(self, ret_oid: bytes):
        """The return object died: drop the holds its serialized reply
        took on the ObjectRefs pickled inside it (mirrors _release_args
        — same _task_arg_refs accounting, same release verdicts)."""
        with self._ref_lock:
            pairs = self._contained_refs.pop(ret_oid, None)
        if not pairs:
            return
        actions = []
        with self._ref_lock:
            for oid, owner in pairs:
                n = self._task_arg_refs.get(oid, 0) - 1
                if n > 0:
                    self._task_arg_refs[oid] = n
                    continue
                self._task_arg_refs.pop(oid, None)
                b_owner = self._borrowed_refs.get(oid)
                if b_owner is not None:
                    if self._local_refs.get(oid, 0) <= 0:
                        self._borrowed_refs.pop(oid, None)
                        actions.append(("remove_borrower", oid, b_owner))
                else:
                    # we own the nested ref: the handoff registered OUR
                    # address in our own borrower set (pinning it against
                    # the executor's racing task-end remove_borrower) —
                    # clear that self-borrow before the zero check
                    s = self._borrowers.get(oid)
                    if s is not None:
                        s.discard(self.address)
                        if not s:
                            self._borrowers.pop(oid, None)
                    if self._ref_gone(oid):
                        actions.append(("release", oid, None))
        for kind, oid, owner in actions:
            if kind == "release":
                self._on_ref_released(oid)
            else:
                asyncio.ensure_future(
                    self._notify_borrow(owner, "remove_borrower", oid))

    def _on_ref_released(self, oid: bytes):
        """Loop thread, owner side: refcount hit zero — free the object
        everywhere (primary-copy unpin WITH store deletion, owner books,
        lineage) instead of leaving it to eviction pressure."""
        with self._ref_lock:
            # re-check: a borrower or a fresh submission may have taken
            # a reference while this release rode the submit buffer
            if not self._ref_gone(oid):
                return
            self._lineage_evicted.discard(oid)
        # a dying return drops the holds on refs its reply contained
        self._release_contained(oid)
        addr = self._pinned_at.pop(oid, None)
        if addr is not None:
            asyncio.ensure_future(self._unpin_at(oid, addr, free=True))
            self._stats_objects_freed += 1
        # a late borrower status query must error, not hang forever on
        # books we just emptied (bounded: blown away wholesale rather
        # than pay per-entry tracking)
        if len(self._freed_objects) > 65536:
            self._freed_objects.clear()
        self._freed_objects.add(oid)
        mem = self.memory_store
        if mem is not None:
            mem.values.pop(oid, None)
            mem.errors.pop(oid, None)
            mem.locations.pop(oid, None)
            mem._events.pop(oid, None)
        task_id = self._lineage_oids.pop(oid, None)
        if task_id is not None and task_id in self._lineage:
            spec, size, oids = self._lineage[task_id]
            if not any(o in self._lineage_oids for o in oids):
                self._lineage.pop(task_id, None)
                self._lineage_bytes -= size
                self._reconstruction_attempts.pop(task_id, None)

    async def _notify_borrow(self, owner_addr: str, method: str,
                             oid: bytes, addr: str | None = None):
        """Borrower -> owner ref-count edge (add_borrower at first
        handle, remove_borrower at last deref). `addr` overrides the
        registered borrower — the return-value handoff registers the
        CALLER, not the executing worker."""
        self._stats_borrower_notifies += 1
        try:
            owner = await self._clients.get(owner_addr)
            await owner.call(method, {
                "object_id": oid, "addr": addr or self.address,
            }, timeout=30.0)
        except (ConnectionLost, RpcError, OSError,
                asyncio.TimeoutError):
            pass  # owner gone: its ref books died with it

    async def _unpin_at(self, oid: bytes, addr: str, free: bool = False):
        # never let an unpin overtake its (async) pin — the raylet
        # would drop the unpin as unknown and the pin would then leak
        pending = self._pending_pins.get(oid)
        if pending is not None:
            await pending
        try:
            raylet = await self._clients.get(addr)
            # free=True: the owner's distributed refcount hit zero — the
            # raylet should delete the store copy outright (refcount
            # permitting), not merely make it evictable
            await raylet.notify("unpin_object",
                                {"object_id": oid, "free": free})
        except (ConnectionLost, RpcError, OSError):
            pass  # raylet gone — nothing left to unpin

    # -- lineage / reconstruction --------------------------------------

    def _retain_lineage(self, spec: task_mod.TaskSpec,
                        plasma_oids: List[bytes]):
        """Keep a re-executable task's spec while its plasma returns are
        referenced (reference: task_manager.h:215 max_lineage_bytes)."""
        if spec.task_type != task_mod.NORMAL_TASK or spec.streaming:
            return  # actor/streaming tasks are not re-executable
        size = sum(len(e[1]) if e[0] == "v" else 64 for e in spec.args) \
            + 256
        oids = [ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                for i in range(spec.num_returns)]
        # re-retains happen on every reconstruction reply — replace, do
        # not double-count
        old = self._lineage.pop(spec.task_id, None)
        if old is not None:
            self._lineage_bytes -= old[1]
        self._lineage[spec.task_id] = (spec, size, oids)
        for oid in plasma_oids:
            self._lineage_oids[oid] = spec.task_id
        self._lineage_bytes += size
        while self._lineage_bytes > self.config.max_lineage_bytes \
                and self._lineage:
            evicted_tid, (old_spec, old_size, old_oids) = \
                self._lineage.popitem(last=False)
            self._lineage_bytes -= old_size
            self._stats_lineage_evictions += 1
            self._reconstruction_attempts.pop(evicted_tid, None)
            for o in old_oids:
                if self._lineage_oids.pop(o, None) is not None:
                    # loss of this object is now permanent — remember
                    # why, so its ObjectLostError can say so
                    with self._ref_lock:
                        self._lineage_evicted.add(o)

    def _fail_lost_object(self, oid: bytes, reason: str | None = None):
        """Fail fast: every waiter on a lost, unreconstructable object
        sees ObjectLostError NOW instead of blocking to its timeout."""
        if reason is None:
            if oid in self._lineage_evicted:
                reason = ("its lineage was evicted past "
                          "max_lineage_bytes, so the producing task "
                          "cannot be re-executed")
            else:
                reason = ("it has no lineage to re-execute (ray.put "
                          "data, actor-method returns and streaming "
                          "items are not reconstructable)")
        self._stats_reconstruction_failures += 1
        self.memory_store.put_error(oid, serialization.dumps(
            ObjectLostError(
                f"object {oid.hex()[:12]} lost: all copies are gone "
                f"and {reason}")))

    async def _reconstruct(self, oid: bytes, depth: int = 0) -> bool:
        """Re-execute the task that created a lost object (reference:
        TaskManager::ResubmitTask + ObjectRecoveryManager), recursively
        recovering missing upstream inputs first. Dedupes concurrent
        recoveries of the same task; resolves when the re-execution's
        reply lands (repopulating locations + pins). Bounded two ways:
        lineage_max_depth on the recursive chain and
        max_object_reconstructions per producing task."""
        task_id = self._lineage_oids.get(oid)
        if task_id is None or task_id not in self._lineage:
            self._fail_lost_object(oid)
            return False
        if depth > self.config.lineage_max_depth:
            self._fail_lost_object(
                oid,
                f"its lineage chain is deeper than lineage_max_depth="
                f"{self.config.lineage_max_depth}")
            return False
        fut = self._reconstructing.get(task_id)
        if fut is None:
            spec, _, oids = self._lineage[task_id]
            attempts = self._reconstruction_attempts.get(task_id, 0)
            if attempts >= self.config.max_object_reconstructions:
                self._fail_lost_object(
                    oid,
                    f"task {spec.name or task_id.hex()[:12]} was "
                    f"already re-executed {attempts}x "
                    f"(max_object_reconstructions)")
                return False
            self._reconstruction_attempts[task_id] = attempts + 1
            # hex()[:12] is only the sha1 prefix shared by every task a
            # submitter mints — include the counter bytes or concurrent
            # recoveries all log as "the same" task
            logger.warning(
                "object %s lost — re-executing task %s (%s), "
                "attempt %d, depth %d",
                oid.hex()[:26], task_id.hex()[:26], spec.name,
                attempts + 1, depth)
            fut = self._loop.create_future()
            self._reconstructing[task_id] = fut
            self._stats_reconstructions += 1
            self._stats_reconstruction_depth_max = max(
                self._stats_reconstruction_depth_max, depth + 1)
            mem = self.memory_store
            for roid in oids:
                # clear each sibling's readiness properly: the event must
                # reset so status waiters block until the new copy lands
                for addr in list(mem.locations.get(roid, [])):
                    mem.drop_location(roid, addr)
                # release surviving sibling pins — a popped-but-not-
                # unpinned entry would hold plasma memory forever
                pinned = self._pinned_at.pop(roid, None)
                if pinned is not None:
                    asyncio.ensure_future(self._unpin_at(roid, pinned))
            # Recover missing upstream inputs FIRST: the re-executed
            # task would otherwise hang pulling a dependency whose only
            # copy died on the same node.
            for dep_oid, dep_owner in spec.plasma_deps():
                if dep_owner not in ("", self.address):
                    continue  # borrowed input: its own owner recovers it
                if dep_oid in mem.values or dep_oid in mem.errors \
                        or mem.locations.get(dep_oid):
                    continue
                if not await self._reconstruct(dep_oid, depth + 1):
                    # upstream unreconstructable: this task's returns
                    # are lost too — fail them with the lineage chain
                    self._reconstructing.pop(task_id, None)
                    if not fut.done():
                        fut.set_result(False)
                    self._stats_reconstruction_failures += 1
                    frame = serialization.dumps(ObjectLostError(
                        f"object {oid.hex()[:12]} lost: its producing "
                        f"task {spec.name or task_id.hex()[:12]} "
                        f"depends on upstream object "
                        f"{dep_oid.hex()[:12]}, which is itself lost "
                        f"and unreconstructable (lineage chain: "
                        f"{spec.name or '?'} <- {dep_oid.hex()[:12]})"))
                    for roid in oids:
                        mem.put_error(roid, frame)
                    return False
            # the re-execution's terminal reply releases arg pins like
            # any submission — take them afresh
            self._retain_args(spec)
            if spec.node_id is not None:
                # a task pinned to the dead node must be free to move
                spec.soft = True
            self._enqueue_task(spec)
        await fut
        return bool(fut.result())

    async def rpc_report_lost_location(self, req):
        """A raylet failed to fetch from a location we advertised: if the
        GCS agrees that node is dead, drop the location, and if that was
        the last copy of a reconstructible object kick off re-execution
        (the caller re-queries status, which then blocks until the new
        copy lands). A transient fetch error to a node the GCS still
        considers alive must NOT drop the location — for objects without
        lineage (puts, actor returns) a wrongly-dropped last copy is
        unrecoverable."""
        oid = req["object_id"]
        addr = req["raylet_addr"]
        if not req.get("authoritative"):
            # third-party report: only trust it if the GCS agrees the
            # node is dead (a raylet reporting about its OWN store is
            # authoritative and skips this)
            try:
                nodes = await self.gcs.call("get_nodes", {}, timeout=10.0)
                alive = {n["raylet_addr"] for n in nodes if n["alive"]}
            except (ConnectionLost, RpcError, OSError,
                    asyncio.TimeoutError):
                return {"ok": False, "still_alive": True}  # can't verify
            if addr in alive:
                return {"ok": False, "still_alive": True}
        self.memory_store.drop_location(oid, addr)
        if oid not in self.memory_store.locations:
            if oid in self._lineage_oids:
                asyncio.ensure_future(self._reconstruct(oid))
            else:
                # unrecoverable: fail every waiter fast instead of
                # letting status queries block to their timeouts
                self._fail_lost_object(oid)
        return {"ok": True}

    # ------------------------------------------------------------------
    # function manager (reference: python/ray/_private/function_manager.py)
    # ------------------------------------------------------------------

    def push_function(self, fn) -> bytes:
        pickled = serialization.dumps(fn)
        key = hashlib.sha1(pickled).digest()[:16]
        self._run_sync(self.gcs.call("kv_put", {
            "ns": "fn:" + self.job_id.hex(),
            "key": key,
            "value": pickled,
            "overwrite": False,
        }))
        return key

    async def _load_function(self, key: bytes):
        if key in self._function_cache:
            return self._function_cache[key]
        reply = await self.gcs.call("kv_get",
                                    {"ns": "fn:" + self.job_id.hex(), "key": key})
        if reply["value"] is None:
            raise RuntimeError(f"function {key.hex()} not found in GCS")
        fn = serialization.loads(reply["value"])
        self._function_cache[key] = fn
        return fn

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        if not tracing.enabled():  # contextmanager costs ~2us/call
            return self._put_impl(value)
        with tracing.span("object.put", kind="producer") as s:
            ref = self._put_impl(value)
            s["attrs"]["object_id"] = ref.hex()[:16]
            return ref

    def _put_impl(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.current_task_id, next(self._put_counter))
        # one-copy put: the serialized value holds only VIEWS (pickle
        # stream + out-of-band buffers); the payload is copied exactly
        # once, directly into the shm frame, on the plasma path below
        sv = serialization.serialize_value(value)
        if sv.size <= self.config.max_direct_call_object_size \
                or self.store is None:
            self._run_sync(self._put_inband(oid.binary(), sv.to_bytes()))
        else:
            # construct the ref (registering the local refcount) BEFORE
            # the pin is recorded — _on_ref_released must find a count
            # to decrement when the user drops the ref
            ref = ObjectRef(oid, self.address)
            self._plasma_put_pinned(oid, sv, wait_pin=False)
            self._run_sync(self._put_plasma_meta(oid.binary()))
            return ref
        return ObjectRef(oid, self.address)

    def _plasma_write(self, write_fn, size: int):
        """Run a plasma write, asking the local raylet to spill pinned
        objects to disk when the arena is full (reference: the raylet's
        CreateRequestQueue spill-on-pressure path). This is what lets the
        store hold more live data than its shm capacity."""
        from ray_tpu._private.object_store import ObjectStoreFullError

        # Grace retries before spilling: a concurrent putter's unpin is
        # usually in flight (release -> raylet) when the arena looks
        # full, and a few ms of patience turns a disk spill into an
        # in-memory eviction. Only after the grace window does the
        # raylet get asked to spill pinned objects to disk.
        for delay in (0.002, 0.01):
            try:
                return write_fn()
            except ObjectStoreFullError:
                time.sleep(delay)
        for _ in range(4):
            try:
                return write_fn()
            except ObjectStoreFullError:
                if self.raylet_addr is None:
                    raise
                freed = self._run_sync(self._request_spill(size))
                if freed == 0:
                    raise
        return write_fn()

    def _plasma_put_pinned(self, oid: ObjectID, sv, wait_pin: bool = True):
        """Create+seal+pin without an unprotected window: the creator's
        store reference (held from create until after the raylet's pin
        lands) is what stops a concurrent writer's eviction from
        destroying the fresh refcount-0 object. Reference: the worker
        pins primary copies through its raylet before the task reply.

        `sv` is a serialization.SerializedValue: the create→write-in-
        place→seal sequence below is the one-copy put protocol — the
        payload moves from the caller's arrays straight into the
        writer-private shm buffer, with no intermediate frame bytes.

        ``wait_pin=False`` (the driver put() fast path) takes the pin
        RPC off the critical path: put returns after seal and the
        create reference is released at the async pin ack. That is only
        safe when the UNPIN is sent by this same process — `_unpin_at`
        awaits `_pending_pins` so an unpin can never overtake its pin.
        Executor task/stream returns MUST wait: their unpin comes from
        the owner, a different process with no view of our in-flight
        pin, so replying before the pin lands would let the owner's
        unpin race ahead of it (pinning the object forever)."""
        def write():
            buf = self.store.create_buffer(oid, sv.size)
            sv.write_into(buf)
            self.store.seal(oid)
            # NOT released yet — we still hold the create reference
        self._plasma_write(write, sv.size)
        fut = asyncio.run_coroutine_threadsafe(
            self._pin_then_release(oid), self._loop)
        if wait_pin:
            fut.result(timeout=35)

    async def _pin_then_release(self, oid: ObjectID):
        key = oid.binary()
        done = self._loop.create_future()
        self._pending_pins[key] = done
        try:
            if self.raylet_addr is not None:
                try:
                    await self._pin_local_async(key)
                except Exception as e:  # noqa: BLE001 — see _pin_local
                    logger.warning(
                        "pin of %s at local raylet failed: %r",
                        key.hex()[:12], e)
        finally:
            self.store.release(oid)
            self._pending_pins.pop(key, None)
            if not done.done():
                done.set_result(None)

    async def _pin_local_async(self, oid: bytes):
        raylet = await self._clients.get(self.raylet_addr)
        await raylet.call("pin_object", {"object_id": oid}, timeout=30.0)

    async def _list_objects_on(self, raylet_addr: str):
        raylet = await self._clients.get(raylet_addr)
        return await raylet.call("list_objects", {}, timeout=30.0)

    async def _store_stats_on(self, raylet_addr: str):
        raylet = await self._clients.get(raylet_addr)
        return await raylet.call("get_store_stats", {}, timeout=30.0)

    async def _request_spill(self, size: int) -> int:
        try:
            raylet = await self._clients.get(self.raylet_addr)
            reply = await raylet.call("spill_objects",
                                      {"needed": size}, timeout=60.0)
            return int(reply.get("freed", 0))
        except (ConnectionLost, RpcError, OSError,
                asyncio.TimeoutError):
            return 0

    async def _put_inband(self, oid: bytes, frame: bytes):
        self.memory_store.put_value(oid, frame)

    async def _put_plasma_meta(self, oid: bytes):
        self.memory_store.add_location(oid, self.raylet_addr)
        # the pin is held or in flight (_plasma_put_pinned; in-flight
        # pins are reconciled with unpins via _pending_pins in
        # _unpin_at); record where, so ref release routes the unpin
        self._pinned_at[oid] = self.raylet_addr

    _FAST_MISS = object()

    def get(self, refs, timeout: float | None = None):
        if not tracing.enabled():
            return self._get_impl(refs, timeout)
        if isinstance(refs, ObjectRef):
            n = 1
        else:
            refs = list(refs)  # materialize: span must not eat the iter
            n = len(refs)
        with tracing.span("object.get", kind="consumer",
                          attrs={"num_refs": n}):
            return self._get_impl(refs, timeout)

    def _get_impl(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        slow: List[Tuple[int, ObjectRef]] = []
        for i, ref in enumerate(ref_list):
            v = self._get_fast(ref, deadline)
            if v is CoreWorker._FAST_MISS:
                slow.append((i, ref))
            out.append(v)
        if slow:
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            values = self._run_sync(
                self._get_async([r for _, r in slow], remaining))
            for (i, _), v in zip(slow, values):
                out[i] = v
        for v in out:
            if isinstance(v, Exception):
                raise v
        return out[0] if single else out

    def _get_fast(self, ref: ObjectRef, deadline: float | None):
        """Caller-thread resolution of owned in-band results: no event-loop
        round trip, and deserialization happens off the loop thread."""
        if ref.owner_addr not in ("", self.address):
            return CoreWorker._FAST_MISS
        oid = ref.binary()
        mem = self.memory_store
        for _ in range(2):
            if oid in mem.errors:
                return self._error_from_frame(mem.errors[oid])
            if oid in mem.values:
                return serialization.loads(mem.values[oid])
            if oid in mem.locations:
                return CoreWorker._FAST_MISS  # plasma: needs the pull path
            waiter = mem.arm_thread_waiter(oid)
            if waiter is None:
                # not a pending owned result (or it just resolved):
                # loop back to re-check the value dicts once
                if mem.ready(oid):
                    continue
                return CoreWorker._FAST_MISS
            t = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                waiter.result(t)
            except (SyncTimeoutError, TimeoutError):
                # distinct types before 3.11 (bpo-44793 unified them)
                raise GetTimeoutError(f"get timed out: {ref}")
        return CoreWorker._FAST_MISS

    async def _get_async(self, refs: Sequence[ObjectRef],
                         timeout: float | None = None) -> List[Any]:
        if len(refs) == 1:  # skip gather's per-ref task wrapping
            return [await self._get_one(refs[0], timeout)]
        return await asyncio.gather(*[self._get_one(r, timeout) for r in refs])

    async def _get_one(self, ref: ObjectRef, timeout: float | None = None):
        oid = ref.binary()
        mem = self.memory_store
        owner_is_self = ref.owner_addr in ("", self.address)

        deadline = None
        if timeout is not None:
            deadline = self._loop.time() + timeout

        def remaining():
            if deadline is None:
                return None
            return max(0.0, deadline - self._loop.time())

        pull_failures = 0
        while True:
            if oid in mem.errors:
                return self._error_from_frame(mem.errors[oid])
            if oid in mem.values:
                return serialization.loads(mem.values[oid])
            if self.store is not None:
                buf = self.store.get_buffer(ObjectID(oid), timeout=-1)
                if buf is not None:
                    return serialization.deserialize(buf)
            if oid in mem.locations:
                # Object lives in remote plasma: ask local raylet to pull it.
                try:
                    await self._pull_via_raylet(ref)
                except (ConnectionLost, RpcError, OSError):
                    # The owner may have declared the object lost while
                    # the pull was in flight (node death swept it):
                    # prefer its verdict — an ObjectLostError naming the
                    # lineage — over the transport error. Owned objects
                    # surface it from mem.errors on the next pass;
                    # borrowed refs drop the stale locations and
                    # re-query the owner, bounded so a persistently
                    # failing pull still raises.
                    if owner_is_self:
                        if oid not in mem.errors and oid not in mem.values:
                            raise
                    else:
                        pull_failures += 1
                        if pull_failures >= 3:
                            raise
                        for addr in list(mem.locations.get(oid, [])):
                            mem.drop_location(oid, addr)
                continue
            if owner_is_self:
                try:
                    await mem.wait_ready(oid, remaining())
                except asyncio.TimeoutError:
                    raise GetTimeoutError(f"get timed out: {ref}")
                continue
            # Borrowed ref: ask the owner for status.
            status = await self._owner_status(ref, remaining())
            if status.get("error"):
                return RayTaskError(status["error"])
            if status["status"] == "inband":
                mem.put_value(oid, status["value"])
            elif status["status"] == "err":
                mem.put_error(oid, status["value"])
            else:
                for addr in status.get("locations", []):
                    mem.add_location(oid, addr)

    async def _owner_status(self, ref: ObjectRef, timeout: float | None):
        owner = await self._clients.get(ref.owner_addr)
        try:
            return await owner.call("get_object_status", {
                "object_id": ref.binary(),
                "wait": True,
            }, timeout=timeout)
        except asyncio.TimeoutError:
            raise GetTimeoutError(f"get timed out: {ref}")

    async def _pull_via_raylet(self, ref: ObjectRef):
        if _fi._PLAN is not None:
            await _fi._PLAN.object_pull()
        raylet = await self._clients.get(self.raylet_addr)
        await raylet.call("pull_object", {
            "object_id": ref.binary(),
            "owner_addr": ref.owner_addr or self.address,
        }, timeout=300.0)

    def _error_from_frame(self, frame: bytes) -> Exception:
        err = serialization.loads(frame)
        if isinstance(err, Exception):
            return err
        return RayTaskError(str(err))

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: float | None = None):
        # Duplicate refs make ready/not_ready partition counts lie
        # (len(ready)+len(not_ready) < len(refs)); the reference rejects
        # them outright (ray.wait, python/ray/_private/worker.py).
        if len({r.binary() for r in refs}) != len(refs):
            raise ValueError("wait() requires a list of unique object refs")
        # Caller-thread fast path: enough refs already visible in the
        # memory store resolves the wait with no loop round-trip — the
        # drain-a-big-batch pattern (`while not_ready: ready, not_ready =
        # wait(not_ready)`) calls wait ~len(refs) times on mostly-ready
        # sets, and a loop hop per call would dominate it.
        mem = self.memory_store
        ready = []
        for ref in refs:
            if mem.ready(ref.binary()):
                ready.append(ref)
                if len(ready) >= num_returns:
                    ready_set = set(ready)
                    return ready, [r for r in refs if r not in ready_set]
        return self._run_sync(self._wait_async(refs, num_returns, timeout))

    async def _wait_async(self, refs, num_returns, timeout):
        """Scan-and-pulse wait: poll readiness synchronously, block on the
        memory store's global completion event between scans. Remote
        (borrowed) refs additionally get a status-driver coroutine whose
        result lands in the memory store — waking the same pulse. The
        API contract (reference ray.wait) caps ready at num_returns."""
        mem = self.memory_store
        deadline = None if timeout is None else self._loop.time() + timeout
        # a driver that FAILS (owner unreachable) counts its ref as
        # ready — the error surfaces at get(), and the wait must not
        # spin forever on a ref that can never resolve
        failed: set = set()

        async def drive(r):
            try:
                await self._ready_one(r)
            except Exception:  # noqa: BLE001 — recorded, surfaced at get
                failed.add(r.binary())
                mem._any_event.set()

        drivers = [asyncio.ensure_future(drive(r))
                   for r in refs if r.owner_addr not in ("", self.address)]
        # plasma membership can change without a memory-store signal
        # (e.g. a local put from another thread): include it in the
        # first scan and in periodic rescans
        scan_plasma = True
        ready: List[ObjectRef] = []
        try:
            while True:
                ready = []
                for r in refs:
                    oid = r.binary()
                    if mem.ready(oid) or oid in failed or (
                            scan_plasma and self.store is not None
                            and self.store.contains(ObjectID(oid))):
                        ready.append(r)
                        if len(ready) >= num_returns:
                            break
                scan_plasma = False
                if len(ready) >= num_returns or len(ready) == len(refs):
                    break  # enough ready, or nothing left to wait on
                if deadline is not None and self._loop.time() >= deadline:
                    break
                t = 0.25
                if deadline is not None:
                    t = min(t, max(0.0, deadline - self._loop.time()))
                try:
                    await mem.wait_any(t)
                except asyncio.TimeoutError:
                    scan_plasma = True  # periodic plasma rescan
        finally:
            for f in drivers:
                f.cancel()
        ready_set = set(ready)
        return ready, [r for r in refs if r not in ready_set]

    async def _ready_one(self, ref: ObjectRef):
        oid = ref.binary()
        mem = self.memory_store
        while True:
            if mem.ready(oid):
                return
            if self.store is not None and self.store.contains(ObjectID(oid)):
                return
            if ref.owner_addr in ("", self.address):
                await mem.wait_ready(oid)
                return
            status = await self._owner_status(ref, None)
            if status["status"] == "inband":
                mem.put_value(oid, status["value"])
            elif status["status"] == "err":
                mem.put_error(oid, status["value"])
            else:
                for addr in status.get("locations", []):
                    mem.add_location(oid, addr)
            return

    def as_future(self, ref: ObjectRef) -> SyncFuture:
        out: SyncFuture = SyncFuture()

        def _done(task: asyncio.Task):
            exc = task.exception()
            if exc is not None:
                out.set_exception(exc)
            else:
                value = task.result()
                if isinstance(value, Exception):
                    out.set_exception(value)
                else:
                    out.set_result(value)

        fut = asyncio.run_coroutine_threadsafe(self._get_one(ref), self._loop)
        fut.add_done_callback(_done)
        return out

    async def await_ref(self, ref: ObjectRef):
        """Used by `await ref` inside async actor methods (runs on the actor
        loop, so delegate to the io loop)."""
        value = await asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(self._get_one(ref), self._loop)
        )
        if isinstance(value, Exception):
            raise value
        return value

    # ------------------------------------------------------------------
    # argument serialization
    # ------------------------------------------------------------------

    def _serialize_args(self, args, kwargs):
        """Returns (wire_args, wire_kwargs, nested_refs) — nested_refs is
        the (oid, owner_addr) list of every ObjectRef a by-value payload
        pickled buried inside a container. Such specs must not join
        multi-task actor batches (see `_actor_enqueue`) even though their
        top-level entries are all by-value, and the owner pins the nested
        refs for the task's lifetime exactly like top-level ref args."""
        nested = []  # local, not self.<attr>: submits are multi-thread
        wire_args = []
        for a in args:
            wire_args.append(self._serialize_arg(a, nested))
        wire_kwargs = {k: self._serialize_arg(v, nested)
                       for k, v in (kwargs or {}).items()}
        return wire_args, wire_kwargs, nested

    def _serialize_arg(self, value, nested=None):
        if isinstance(value, ObjectRef):
            oid = value.binary()
            mem = self.memory_store
            # Inline owner-local in-band values (reference:
            # LocalDependencyResolver inlines memory-store objects).
            if oid in mem.values:
                return ["v", mem.values[oid]]
            return ["r", oid, value.owner_addr or self.address]
        payload, refs = serialization.dumps_with_ref_flag(value)
        if refs and nested is not None:
            nested.extend(
                (r.binary(), r.owner_addr or self.address) for r in refs)
        return ["v", payload]

    @staticmethod
    def _args_all_inline(spec: task_mod.TaskSpec) -> bool:
        return (all(e[0] == "v" for e in spec.args)
                and all(e[0] == "v" for e in spec.kwargs.values()))

    @classmethod
    def _batchable(cls, spec: task_mod.TaskSpec) -> bool:
        """A spec may ride a multi-task actor batch only if it depends on
        no other object: no top-level by-ref args AND no ObjectRef nested
        inside a by-value container (the submit side stamps
        `_nested_refs`; specs built elsewhere default to unbatchable only
        when the stamp is absent and args are refs)."""
        return (not getattr(spec, "_nested_refs", False)
                and cls._args_all_inline(spec))

    @staticmethod
    def _deserialize_inline_args(spec: task_mod.TaskSpec):
        """Caller/executor-thread decode of all-inline args: pure CPU, no
        event-loop round trip (the hot path — most tasks ship only
        by-value args)."""
        args = [serialization.loads(e[1]) for e in spec.args]
        kwargs = {k: serialization.loads(e[1])
                  for k, e in spec.kwargs.items()}
        return args, kwargs

    async def _deserialize_args(self, spec: task_mod.TaskSpec):
        async def resolve(entry):
            if entry[0] == "v":
                return serialization.loads(entry[1])
            ref = ObjectRef(ObjectID(entry[1]), entry[2])
            value = await self._get_one(ref)
            if isinstance(value, Exception):
                raise value
            return value

        args = [await resolve(e) for e in spec.args]
        kwargs = {k: await resolve(e) for k, e in spec.kwargs.items()}
        return args, kwargs

    # ------------------------------------------------------------------
    # normal task submission (CoreWorkerDirectTaskSubmitter)
    # ------------------------------------------------------------------

    def submit_task(
        self,
        function_key: bytes,
        args: tuple,
        kwargs: dict,
        name: str = "",
        num_returns: int = 1,
        resources: Dict[str, float] | None = None,
        max_retries: int | None = None,
        strategy: str = task_mod.STRATEGY_DEFAULT,
        node_id: bytes | None = None,
        soft: bool = False,
        placement_group_id: bytes | None = None,
        bundle_index: int = -1,
        streaming: bool = False,
        runtime_env: dict | None = None,
    ):
        task_id = TaskID.of(self.job_id, self.current_task_id,
                            next(self._task_counter))
        if not tracing.enabled():  # contextmanager costs ~2us/call
            return self._submit_task_traced(
                task_id, None, function_key, args, kwargs, name,
                num_returns, resources, max_retries, strategy, node_id,
                soft, placement_group_id, bundle_index, streaming,
                runtime_env)
        with tracing.submit_span(name, task_mod.NORMAL_TASK) as trace_ctx:
            return self._submit_task_traced(
                task_id, trace_ctx, function_key, args, kwargs, name,
                num_returns, resources, max_retries, strategy, node_id,
                soft, placement_group_id, bundle_index, streaming,
                runtime_env)

    def _submit_task_traced(
        self, task_id, trace_ctx, function_key, args, kwargs, name,
        num_returns, resources, max_retries, strategy, node_id, soft,
        placement_group_id, bundle_index, streaming, runtime_env,
    ):
        wire_args, wire_kwargs, nested_refs = \
            self._serialize_args(args, kwargs)
        spec = task_mod.TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=name,
            trace_ctx=trace_ctx,
            _nested_refs=nested_refs,
            task_type=task_mod.NORMAL_TASK,
            function_key=function_key,
            args=wire_args,
            kwargs=wire_kwargs,
            num_returns=0 if streaming else num_returns,
            resources=resources or {"CPU": 1.0},
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            strategy=strategy,
            node_id=node_id,
            soft=soft,
            placement_group_id=placement_group_id,
            bundle_index=bundle_index,
            # a partially-consumed stream cannot be transparently
            # re-executed — streaming tasks are never retried
            max_retries=0 if streaming else (
                self.config.task_max_retries_default
                if max_retries is None else max_retries),
            streaming=streaming,
            runtime_env=runtime_env,
        )
        if self.mode == "worker":
            # recursive-cancel bookkeeping: this spec is a child of the
            # task executing on this thread/coroutine (context-local, so
            # concurrent actor tasks attribute correctly); entries are
            # popped when the parent finishes
            parent = _executing_task_id.get()
            if parent is not None:
                self._task_children.setdefault(parent, []).append(
                    spec.task_id)
        # pin by-ref args for the task's lifetime BEFORE the enqueue —
        # the caller may drop its handles the moment `.remote()` returns
        self._retain_args(spec)
        if streaming:
            # plain dict insert; ordered before the task via the same
            # submit-buffer flush the enqueue rides on
            self._make_stream(spec.task_id)
            self._submit_enqueue("normal", spec)
            return ObjectRefGenerator(self, spec.task_id)
        refs = [
            ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
            for i in range(num_returns)
        ]
        for r in refs:
            self.memory_store.register_thread_waiter(r.binary())
        self._submit_enqueue("normal", spec)
        return refs

    # ------------------------------------------------------------------
    # streaming generators (num_returns="streaming")
    #
    # Reference: `_raylet.pyx:273` ObjectRefGenerator +
    # `ReportGeneratorItemReturns` (core_worker.proto:462) + the
    # generator_waiter.h backpressure. The executor reports each yielded
    # item to the owner as it is produced; the owner buffers item refs,
    # withholding the ack once `streaming_backpressure_items` are
    # unconsumed so a slow consumer throttles the producer. Early close()
    # tells the executor to stop at the next report.
    # ------------------------------------------------------------------

    def _make_stream(self, task_id: bytes) -> dict:
        st = self._streams[task_id] = {
            "items": deque(),      # ObjectRefs ready to hand out
            "done": False,         # no more items will arrive
            "error": None,         # stream-level failure (Exception)
            "cancelled": False,
            "new_item": asyncio.Event(),   # owner-loop waiters
            "drained": asyncio.Event(),    # backpressure release
        }
        return st

    #: coroutine-safe exhaustion marker — StopIteration cannot cross a
    #: coroutine boundary (PEP 479 turns it into RuntimeError)
    _STREAM_DONE = object()

    def stream_next(self, task_id: bytes,
                    timeout: float | None = None) -> ObjectRef:
        """Block (caller thread) for the next item ref of a stream.
        Raises StopIteration when the stream completed, or the stream
        error."""
        out = self._run_sync(self._stream_next_async(task_id, timeout),
                             timeout=None)
        if out is CoreWorker._STREAM_DONE:
            raise StopIteration
        return out

    async def _stream_next_async(self, task_id: bytes,
                                 timeout: float | None = None):
        """Returns the next ObjectRef, or _STREAM_DONE on exhaustion."""
        st = self._streams.get(task_id)
        if st is None:
            return CoreWorker._STREAM_DONE
        deadline = None if timeout is None else self._loop.time() + timeout
        while True:
            if st["items"]:
                ref = st["items"].popleft()
                if len(st["items"]) < \
                        self.config.streaming_backpressure_items:
                    st["drained"].set()
                return ref
            if st["error"] is not None:
                self._streams.pop(task_id, None)
                raise st["error"]
            if st["done"] or st["cancelled"]:
                self._streams.pop(task_id, None)
                return CoreWorker._STREAM_DONE
            st["new_item"].clear()
            wait_for = None
            if deadline is not None:
                wait_for = max(0.0, deadline - self._loop.time())
                if wait_for == 0.0:
                    raise GetTimeoutError(
                        f"stream item not ready within {timeout}s")
            try:
                await asyncio.wait_for(st["new_item"].wait(), wait_for)
            except asyncio.TimeoutError:
                raise GetTimeoutError(
                    f"stream item not ready within {timeout}s") from None

    def stream_cancel(self, task_id: bytes):
        """Stop the producer at its next report (early generator close).
        Also the terminal cleanup: close() means no further next() calls,
        so the stream dict and any unconsumed buffered item values are
        reclaimed here — a long-lived proxy must not accumulate state per
        aborted stream."""
        def _cancel():
            st = self._streams.pop(task_id, None)
            if st is not None:
                st["cancelled"] = True
                st["drained"].set()
                st["new_item"].set()
                mem = self.memory_store
                for ref in st["items"]:
                    oid = ref.binary()
                    mem.values.pop(oid, None)
                    mem.errors.pop(oid, None)
                    mem._events.pop(oid, None)
        self._loop.call_soon_threadsafe(_cancel)

    async def rpc_report_stream_item(self, req):
        """Owner-side: the executor reports one yielded item (reference:
        HandleReportGeneratorItemReturns). The reply doubles as the
        backpressure ack — withheld while the buffer is full — and
        carries the cancellation flag back to the producer."""
        task_id = req["task_id"]
        st = self._streams.get(task_id)
        if st is None or st["cancelled"]:
            return {"ok": True, "cancelled": True}
        oid, kind, payload = req["item"]
        mem = self.memory_store
        if kind == "v":
            mem.put_value(oid, payload)
        elif kind == "err":
            mem.put_error(oid, payload)
        else:  # plasma
            mem.add_location(oid, payload)
            # the executor pinned the item at its raylet; record the
            # mapping so the consumer's ref release unpins it
            self._pinned_at[oid] = payload
        st["items"].append(ObjectRef(ObjectID(oid), self.address))
        st["new_item"].set()
        while (len(st["items"]) >=
               self.config.streaming_backpressure_items
               and not st["cancelled"]):
            st["drained"].clear()
            await st["drained"].wait()
        return {"ok": True, "cancelled": st["cancelled"]}

    def _finish_stream(self, task_id: bytes,
                       error: Exception | None = None):
        st = self._streams.get(task_id)
        if st is None:
            return
        if error is not None and st["error"] is None:
            st["error"] = error
        st["done"] = True
        st["new_item"].set()

    def _enqueue_task(self, spec: task_mod.TaskSpec):
        self._emit_task_event(spec.task_id, spec.name, spec.task_type,
                              "SUBMITTED")
        key = spec.scheduling_key()
        state = self._key_states.get(key)
        if state is None:
            state = self._key_states[key] = _KeyState()
        state.queue.append([spec, spec.max_retries])
        # Pipeline through a bounded set of leases (reference: the
        # submitter caps in-flight lease requests per SchedulingKey).
        # One request per queued task would flood the raylet into
        # spawning far more workers than cores under bursty submission.
        cap = min(max(1, len(state.queue)),
                  self.config.max_lease_requests_per_key)
        if state.requesting < cap:
            state.requesting += 1
            asyncio.ensure_future(self._lease_and_run(key, state))

    async def _lease_and_run(self, key, state: _KeyState):
        try:
            while state.queue:
                spec0 = state.queue[0][0]
                lease = await self._request_lease(spec0)
                if lease is None or not lease.get("granted"):
                    if state.queue:
                        entry = state.queue.popleft()
                        self._store_task_error(
                            entry[0],
                            RayTaskError(
                                "scheduling failed: "
                                + str((lease or {}).get("error", "no lease"))
                            ),
                        )
                    continue
                await self._drain_with_lease(key, state, lease)
        finally:
            state.requesting -= 1

    async def _pg_bundle_addr(self, pg_id: bytes, bundle_index: int) -> str:
        """Route a PG-targeted lease to the raylet hosting the bundle
        (reference: the submitter's lease policy consults the placement
        group's location)."""
        deadline = self._loop.time() + 300.0
        while True:
            reply = await self.gcs.call("get_placement_group", {"pg_id": pg_id})
            if reply.get("found") and reply["state"] == "CREATED":
                break
            if reply.get("found") and reply["state"] == "REMOVED":
                raise RayTaskError("placement group was removed")
            if self._loop.time() > deadline:
                raise RayTaskError("placement group never became ready")
            await asyncio.sleep(0.05)
        nodes = await self.gcs.call("get_nodes", {})
        addr_by_id = {n["node_id"]: n["raylet_addr"] for n in nodes if n["alive"]}
        index = bundle_index if bundle_index >= 0 else 0
        node_id = reply["bundle_nodes"][index]
        if node_id not in addr_by_id:
            raise RayTaskError("placement group bundle node is dead")
        return addr_by_id[node_id]

    async def _request_lease(self, spec: task_mod.TaskSpec, max_hops: int = 4):
        addr = self.raylet_addr
        no_spillback = False
        if spec.placement_group_id is not None:
            try:
                addr = await self._pg_bundle_addr(
                    spec.placement_group_id, spec.bundle_index
                )
            except RayTaskError as e:
                return {"granted": False, "error": str(e)}
            no_spillback = True
        conn_retries = 0
        hops = 0
        while hops < max_hops:
            hops += 1
            try:
                raylet = await self._clients.get(addr)
                reply = await raylet.call("request_worker_lease", {
                    "spec": spec.to_wire(),
                    "no_spillback": no_spillback,
                }, timeout=300.0)
            except RpcError as e:
                # the peer is ALIVE and replied with an error — never a
                # connectivity retry case
                return {"granted": False, "error": str(e)}
            except (ConnectionLost, OSError) as e:
                if (spec.placement_group_id is None
                        and addr != self.raylet_addr
                        and conn_retries < 15):
                    # A dead/unreachable REMOTE hop (spillback target or
                    # soft-affinity node that died between the scheduling
                    # decision and the lease): wait for the GCS to prune
                    # it from the view, then re-route from the local
                    # raylet — failing the task here would turn a node
                    # death into a permanent task error even though
                    # other capacity exists (lineage reconstruction hits
                    # exactly this window). Each cycle resets the hop
                    # budget — the reroute itself consumes local->target
                    # hops. PG-targeted leases are excluded: their
                    # bundle's death is the PG machinery's to handle.
                    conn_retries += 1
                    hops = 0
                    addr = self.raylet_addr
                    no_spillback = False
                    await asyncio.sleep(1.0)
                    continue
                return {"granted": False, "error": str(e)}
            if reply.get("granted"):
                reply["raylet_addr"] = addr
                return reply
            if reply.get("spillback_addr"):
                addr = reply["spillback_addr"]
                no_spillback = True
                continue
            return reply
        return {"granted": False, "error": "too many spillback hops"}

    async def _drain_with_lease(self, key, state: _KeyState, lease: dict):
        """Drain the key's queue through one leased worker with a bounded
        pipeline: up to `max_tasks_in_flight_per_worker` pushes ride the
        connection before the first reply returns (reference: lease
        pipelining in direct_task_transport.h:75). The worker executes
        FIFO, so replies resolve in push order."""
        worker_addr = lease["worker_addr"]
        raylet_addr = lease["raylet_addr"]
        lease_id = lease["lease_id"]
        worker_dead = False
        # SPREAD asks for per-task placement decisions: pipelining the
        # queue through one cached lease would funnel every task onto the
        # first node that answered. One task per lease; the caller loop
        # re-requests for the rest. (The whole queue shares one strategy:
        # it's part of the scheduling key.)
        depth = (1 if state.queue
                 and state.queue[0][0].strategy == task_mod.STRATEGY_SPREAD
                 else self.config.max_tasks_in_flight_per_worker)
        in_flight: deque = deque()  # ([(spec, retries_left), ...], fut)
        n_inflight = 0
        try:
            try:
                worker = await self._clients.get(worker_addr)
            except (ConnectionLost, OSError):
                # never connected: nothing sent, nothing to fail — the
                # caller loop re-leases for the still-queued tasks
                worker_dead = True
                return
            while state.queue or in_flight:
                # Pipeline only the queue's fair share per outstanding
                # lease: a short queue spread over several pending leases
                # must not funnel onto the first worker that answers
                # (that would serialize long tasks that could have run in
                # parallel), while a long queue pipelines deep to
                # amortize the push round trip. Everything the window
                # admits in one go rides ONE batch frame (the executor
                # enqueues the whole batch before replying) — per-task
                # frames would pay a syscall each way per task.
                share = max(1, len(state.queue)
                            // max(1, state.requesting))
                window = min(depth, share)
                while state.queue and n_inflight < window:
                    if state.queue[0][0].task_id in self._cancelled_tasks:
                        spec, _ = state.queue.popleft()
                        self._store_task_error(
                            spec, TaskCancelledError("task was cancelled"))
                        continue
                    take = min(window - n_inflight, len(state.queue))
                    # Only dependency-free specs may share a frame: the
                    # batch's single reply is withheld until every task
                    # in it finishes, so a spec whose ref args resolve
                    # via THIS owner could deadlock on an earlier
                    # batchmate's in-band return (same rule as the actor
                    # fast path — see _actor_enqueue). A spec with deps
                    # rides alone.
                    if not self._batchable(state.queue[0][0]):
                        batch = [state.queue.popleft()]
                    else:
                        batch = []
                        while (state.queue and len(batch) < take
                               and self._batchable(state.queue[0][0])
                               and state.queue[0][0].task_id
                               not in self._cancelled_tasks):
                            batch.append(state.queue.popleft())
                    try:
                        if len(batch) == 1:
                            fut = worker.call_nowait(
                                "push_task",
                                {"spec": batch[0][0].to_wire()})
                        else:
                            fut = worker.call_nowait(
                                "push_task_batch",
                                {"specs": [b[0].to_wire()
                                           for b in batch]})
                    except (ConnectionLost, OSError):
                        # not sent: requeue without burning a retry
                        for b in reversed(batch):
                            state.queue.appendleft(b)
                        worker_dead = True
                        break
                    in_flight.append((batch, fut))
                    n_inflight += len(batch)
                    for b in batch:
                        self._inflight_tasks[b[0].task_id] = worker_addr
                if not in_flight:
                    return
                batch, fut = in_flight.popleft()
                n_inflight -= len(batch)
                try:
                    replies = await fut
                except (ConnectionLost, RpcError, OSError) as e:
                    # The worker executes FIFO, so only the batch whose
                    # reply we were awaiting can contain tasks that
                    # started executing — each burns a retry (it may
                    # have run) and carries the OOM blame. Batches
                    # pushed behind it never started: requeue without
                    # burning a retry, like the never-sent case above.
                    # (A reply lost in transit could in principle mean
                    # the next batch also started — same at-most-once
                    # race the reference accepts.)
                    worker_dead = True
                    oom_reason = await self._worker_exit_reason(
                        raylet_addr, worker_addr)
                    for later_batch, f in in_flight:
                        # mark retrieved — abandoned reply futures would
                        # otherwise log "exception was never retrieved"
                        f.add_done_callback(
                            lambda fut: fut.cancelled() or fut.exception())
                        state.queue.extend(later_batch)
                        for b in later_batch:
                            self._inflight_tasks.pop(b[0].task_id, None)
                    in_flight.clear()
                    n_inflight = 0
                    for spec, retries_left in batch:
                        self._inflight_tasks.pop(spec.task_id, None)
                        if spec.task_id in self._cancelled_tasks:
                            # a force-cancel kills the worker: the lost
                            # connection IS the cancellation succeeding
                            self._store_task_error(
                                spec,
                                TaskCancelledError("task was cancelled"))
                        elif retries_left > 0:
                            state.queue.append([spec, retries_left - 1])
                        elif oom_reason:
                            self._store_task_error(
                                spec, OutOfMemoryError(oom_reason))
                        else:
                            self._store_task_error(
                                spec, RayTaskError(f"worker died: {e}"))
                    return
                if len(batch) == 1:
                    replies = [replies]
                for (spec, _), reply in zip(batch, replies):
                    self._inflight_tasks.pop(spec.task_id, None)
                    self._process_task_reply(spec, reply)
                if depth == 1:
                    return  # SPREAD: one task per lease
        finally:
            try:
                raylet = await self._clients.get(raylet_addr)
                # fire-and-forget: the reply was never used, and frames on
                # one connection are FIFO, so the raylet processes the
                # return before any subsequent lease request from this
                # owner — dropping the await removes one round trip per
                # lease cycle (and the notify rides the write coalescer)
                await raylet.notify("return_worker", {
                    "lease_id": lease_id,
                    "worker_dead": worker_dead,
                })
            except (ConnectionLost, RpcError, OSError):
                pass

    async def _worker_exit_reason(self, raylet_addr: str,
                                  worker_addr: str) -> str | None:
        """Ask the worker's raylet whether it killed the worker on
        purpose (memory monitor) — turns a connection loss into an
        actionable OutOfMemoryError."""
        try:
            raylet = await self._clients.get(raylet_addr)
            reply = await raylet.call("get_worker_exit_reason",
                                      {"worker_addr": worker_addr},
                                      timeout=5.0)
            return reply.get("reason")
        except (ConnectionLost, RpcError, OSError,
                asyncio.TimeoutError):
            return None

    def _process_task_reply(self, spec: task_mod.TaskSpec, reply: dict):
        self._emit_task_event(
            spec.task_id, spec.name, spec.task_type,
            "FAILED" if reply.get("error") else "FINISHED")
        self._cancelled_tasks.pop(spec.task_id, None)  # terminal
        self._release_args(spec)  # drop the submitted-task arg pins
        mem = self.memory_store
        # Return values carrying ObjectRefs: the executor registered us
        # as borrower of each before replying; hold them until the
        # return object itself dies (the serialized reply contains the
        # ref whether or not we ever deserialize a handle).
        for ret_oid, pairs in reply.get("ref_handoffs", []):
            with self._ref_lock:
                for oid, owner in pairs:
                    self._task_arg_refs[oid] = \
                        self._task_arg_refs.get(oid, 0) + 1
                    if owner != self.address \
                            and oid not in self._borrowed_refs:
                        self._borrowed_refs[oid] = owner
                self._contained_refs.setdefault(ret_oid, []).extend(
                    [tuple(p) for p in pairs])
                gone = self._ref_gone(ret_oid)
            if gone:
                # the return's handle died before the reply landed —
                # nothing will ever trigger the containment release
                self._release_contained(ret_oid)
        plasma_oids: List[bytes] = []
        for entry in reply.get("returns", []):
            oid, kind, payload = entry
            if kind == "v":
                mem.put_value(oid, payload)
            elif kind == "err":
                mem.put_error(oid, payload)
            elif kind == "plasma":
                mem.add_location(oid, payload)
                plasma_oids.append(oid)
                # the executor pinned the return at its raylet before
                # replying — record the mapping only while someone still
                # holds a reference (decide under the ref lock, act on
                # the verdict outside it; a deref racing the record
                # enqueues a release that re-checks and unpins)
                with self._ref_lock:
                    referenced = not self._ref_gone(oid)
                if referenced:
                    self._pinned_at[oid] = payload
                else:
                    asyncio.ensure_future(
                        self._unpin_at(oid, payload, free=True))
        if plasma_oids:
            self._retain_lineage(spec, plasma_oids)
            for oid in plasma_oids:
                with self._ref_lock:
                    gone = self._ref_gone(oid)
                if gone:
                    self._on_ref_released(oid)  # ref died pre-reply
        fut = self._reconstructing.pop(spec.task_id, None)
        if fut is not None and not fut.done():
            fut.set_result(not reply.get("error"))
        if spec.streaming:
            # the final reply closes the stream; pre-execution failures
            # arrive as an error entry instead of item reports
            err = None
            for entry in reply.get("returns", []):
                if entry[1] == "err":
                    err = self._error_from_frame(entry[2])
                    break
            self._finish_stream(spec.task_id, err)

    def _store_task_error(self, spec: task_mod.TaskSpec, err: Exception):
        self._emit_task_event(spec.task_id, spec.name, spec.task_type,
                              "FAILED")
        self._cancelled_tasks.pop(spec.task_id, None)  # terminal
        self._release_args(spec)
        fut = self._reconstructing.pop(spec.task_id, None)
        if fut is not None and not fut.done():
            fut.set_result(False)
        if spec.streaming:
            self._loop.call_soon_threadsafe(
                self._finish_stream, spec.task_id, err)
            return
        frame = serialization.dumps(err)
        for i in range(spec.num_returns):
            oid = ObjectID.for_task_return(TaskID(spec.task_id), i)
            self.memory_store.put_error(oid.binary(), frame)

    # ------------------------------------------------------------------
    # actor submission (CoreWorkerDirectActorTaskSubmitter)
    # ------------------------------------------------------------------

    def create_actor(
        self,
        class_key: bytes,
        args: tuple,
        kwargs: dict,
        name: str = "",
        actor_name: str | None = None,
        resources: Dict[str, float] | None = None,
        max_restarts: int = 0,
        max_concurrency: int = 1,
        detached: bool = False,
        strategy: str = task_mod.STRATEGY_DEFAULT,
        node_id: bytes | None = None,
        soft: bool = False,
        placement_group_id: bytes | None = None,
        bundle_index: int = -1,
        runtime_env: dict | None = None,
        concurrency_groups: Dict[str, int] | None = None,
    ) -> ActorID:
        actor_id = ActorID.of(self.job_id, self.current_task_id,
                              next(self._task_counter))
        task_id = TaskID.of(self.job_id, self.current_task_id,
                            next(self._task_counter), actor_id)
        wire_args, wire_kwargs, _ = self._serialize_args(args, kwargs)
        spec = task_mod.TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=name,
            task_type=task_mod.ACTOR_CREATION_TASK,
            function_key=class_key,
            args=wire_args,
            kwargs=wire_kwargs,
            num_returns=0,
            resources=resources or {"CPU": 1.0},
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            actor_id=actor_id.binary(),
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            strategy=strategy,
            node_id=node_id,
            soft=soft,
            placement_group_id=placement_group_id,
            bundle_index=bundle_index,
            detached=detached,
            actor_name=actor_name,
            runtime_env=runtime_env,
            concurrency_groups=concurrency_groups,
        )
        reply = self._run_sync(
            self.gcs.call("register_actor", {"spec": spec.to_wire()})
        )
        if not reply.get("ok"):
            raise ValueError(reply.get("error", "actor registration failed"))
        return actor_id

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
        streaming: bool = False,
        concurrency_group: str = "",
    ):
        task_id = TaskID.of(self.job_id, self.current_task_id,
                            next(self._task_counter), actor_id)
        if not tracing.enabled():
            return self._submit_actor_task_traced(
                actor_id, task_id, None, method_name, args, kwargs,
                num_returns, streaming, concurrency_group)
        with tracing.submit_span(method_name,
                                 task_mod.ACTOR_TASK) as trace_ctx:
            return self._submit_actor_task_traced(
                actor_id, task_id, trace_ctx, method_name, args, kwargs,
                num_returns, streaming, concurrency_group)

    def _submit_actor_task_traced(self, actor_id, task_id, trace_ctx,
                                  method_name, args, kwargs, num_returns,
                                  streaming, concurrency_group=""):
        wire_args, wire_kwargs, nested_refs = \
            self._serialize_args(args, kwargs)
        spec = task_mod.TaskSpec(
            task_id=task_id.binary(),
            job_id=self.job_id.binary(),
            name=method_name,
            trace_ctx=trace_ctx,
            task_type=task_mod.ACTOR_TASK,
            args=wire_args,
            kwargs=wire_kwargs,
            num_returns=0 if streaming else num_returns,
            owner_addr=self.address,
            owner_worker_id=self.worker_id.binary(),
            actor_id=actor_id.binary(),
            method_name=method_name,
            streaming=streaming,
            concurrency_group=concurrency_group,
        )
        spec._nested_refs = nested_refs
        self._retain_args(spec)
        if streaming:
            self._make_stream(spec.task_id)
            self._submit_enqueue("actor", spec)
            return ObjectRefGenerator(self, spec.task_id)
        refs = [
            ObjectRef(ObjectID.for_task_return(task_id, i), self.address)
            for i in range(num_returns)
        ]
        for r in refs:
            self.memory_store.register_thread_waiter(r.binary())
        self._submit_enqueue("actor", spec)
        return refs

    def _actor_state(self, actor_id: bytes) -> dict:
        st = self._actor_clients.get(actor_id)
        if st is None:
            st = self._actor_clients[actor_id] = {
                "queue": deque(),
                "sending": False,
                "seq": 0,
                "epoch": 0,
                "instance": None,  # (addr, num_restarts) of the live actor
            }
        return st

    def _submit_enqueue(self, kind: str, spec: task_mod.TaskSpec):
        """Caller-thread side of submission: buffer the spec and make sure
        ONE flush callback is scheduled. A burst of `.remote()` calls from
        a tight loop lands in a single loop wakeup, and the flush batches
        same-actor tasks into one RPC frame."""
        self._submit_buffer.append((kind, spec))
        if not self._submit_flush_scheduled:
            self._submit_flush_scheduled = True
            self._loop.call_soon_threadsafe(self._flush_submissions)

    def _flush_submissions(self):
        # clear-then-drain: a producer appending after the clear schedules
        # a fresh flush, so no submission is ever stranded in the buffer
        self._submit_flush_scheduled = False
        batches: Dict[bytes, list] = {}  # actor_id -> [st,addr,restarts,client,[specs]]
        while True:
            try:
                kind, spec = self._submit_buffer.popleft()
            except IndexError:
                break
            if kind == "normal":
                self._enqueue_task(spec)
            elif kind == "actor":
                self._actor_enqueue(spec, batches)
            elif kind in ("add_borrower", "remove_borrower"):
                # spec is (oid, owner_addr) — borrower-side ref edge
                asyncio.ensure_future(
                    self._notify_borrow(spec[1], kind, spec[0]))
            else:  # "release": spec is the released object id
                self._on_ref_released(spec)
        for entry in batches.values():
            self._send_actor_batch(*entry)

    def _actor_enqueue(self, spec: task_mod.TaskSpec,
                       batches: Dict[bytes, list] | None = None):
        self._emit_task_event(spec.task_id, spec.name, spec.task_type,
                              "SUBMITTED")
        st = self._actor_state(spec.actor_id)
        # A spec with by-reference args — top-level OR nested inside a
        # by-value container — must NEVER ride a multi-task batch: the
        # batch's single reply is withheld until every task finishes, but
        # resolving this spec's ref args (via get() in the task body for
        # nested ones) may need the in-band return of an EARLIER task in
        # the same batch (whose value only arrives in that withheld
        # reply) — deadlock. Send it as its own frame so upstream replies
        # flow independently.
        if batches is not None and not self._batchable(spec):
            # first send whatever batch already accumulated for this
            # actor (its tasks precede this one in submission order)...
            entry = batches.pop(spec.actor_id, None)
            if entry is not None:
                self._send_actor_batch(*entry)
            # ...then fall through with batching disabled for this spec
            batches = None
        if batches is not None:
            entry = batches.get(spec.actor_id)
            if entry is not None:
                # this flush already fast-paths this actor: ride the batch
                entry[4].append(spec)
                return
        # Fast path: actor resolved, connection live, nothing queued — write
        # the frame at the end of this flush, skipping the sender/push
        # coroutine hops. The executing side reorders by (epoch, seq) per
        # caller, so this cannot race the slow path on ordering.
        if not st["sending"] and not st["queue"] and st.get("instance"):
            addr, restarts = st["instance"]
            client = self._clients.get_cached(addr)
            if client is not None:
                if batches is not None:
                    batches[spec.actor_id] = [st, addr, restarts, client,
                                              [spec]]
                else:
                    self._send_actor_batch(st, addr, restarts, client,
                                           [spec])
                return
        st["queue"].append(spec)
        if not st["sending"]:
            st["sending"] = True
            asyncio.ensure_future(self._actor_sender(spec.actor_id, st))

    def _send_actor_batch(self, st: dict, addr: str, restarts: int,
                          client, specs: list):
        """Write one frame carrying every fast-path task this flush
        collected for one actor. Sequence numbers are assigned here, in
        buffer order."""
        for spec in specs:
            self._assign_seq(st, addr, restarts, spec)
        try:
            if len(specs) == 1:
                fut = client.call_nowait("push_task",
                                         {"spec": specs[0].to_wire()})
            else:
                fut = client.call_nowait(
                    "push_task_batch",
                    {"specs": [s.to_wire() for s in specs]})
        except (ConnectionLost, OSError) as e:
            for spec in specs:
                self._actor_task_failed(st, spec, addr, e)
            return
        for spec in specs:
            self._inflight_tasks[spec.task_id] = addr
        if len(specs) == 1:
            fut.add_done_callback(
                lambda f, spec=specs[0], st=st, addr=addr:
                self._actor_fast_reply(f, spec, st, addr))
        else:
            fut.add_done_callback(
                lambda f, specs=specs, st=st, addr=addr:
                self._actor_batch_reply(f, specs, st, addr))

    def _actor_batch_reply(self, fut: asyncio.Future, specs: list,
                           st: dict, addr: str):
        try:
            replies = fut.result()
        except (ConnectionLost, RpcError, OSError) as e:
            for spec in specs:
                self._actor_task_failed(st, spec, addr, e)
            return
        for spec, reply in zip(specs, replies):
            self._inflight_tasks.pop(spec.task_id, None)
            self._process_task_reply(spec, reply)

    def _assign_seq(self, st: dict, addr: str, restarts: int,
                    spec: task_mod.TaskSpec):
        """Assign (epoch, seq) against the current actor instance. The epoch
        bumps whenever numbering restarts — new actor instance or reconnect
        after failure — so the executor can resync instead of waiting on a
        seq that died with the old connection."""
        instance = (addr, restarts)
        if st.get("seq_instance") != instance:
            st["seq_instance"] = instance
            st["epoch"] += 1
            st["seq"] = 0
        spec.seq_no = st["seq"]
        spec.seq_epoch = st["epoch"]
        st["seq"] += 1

    def _actor_task_failed(self, st: dict, spec: task_mod.TaskSpec,
                           addr: str, exc: Exception):
        """Shared failure handling for fast- and slow-path pushes: invalidate
        the cached instance AND the seq instance (forcing an epoch bump on
        the next send), then error the task — actor tasks are never
        implicitly re-executed."""
        if st.get("instance") and st["instance"][0] == addr:
            st["instance"] = None
        st["seq_instance"] = None
        self._inflight_tasks.pop(spec.task_id, None)
        if spec.task_id in self._cancelled_tasks:
            # force-cancel took the worker down mid-call: report the
            # cancellation, not a spurious actor death
            self._store_task_error(
                spec, TaskCancelledError("task was cancelled"))
            return
        self._store_task_error(
            spec,
            ActorDiedError(
                f"actor task {spec.method_name} failed (actor died "
                f"mid-call, not retried): {exc}"
            ),
        )

    def _actor_fast_reply(self, fut: asyncio.Future,
                          spec: task_mod.TaskSpec, st: dict, addr: str):
        try:
            reply = fut.result()
        except (ConnectionLost, RpcError, OSError) as e:
            self._actor_task_failed(st, spec, addr, e)
            return
        self._inflight_tasks.pop(spec.task_id, None)
        self._process_task_reply(spec, reply)

    async def _actor_sender(self, actor_id: bytes, st: dict):
        """Ordered, pipelined sends: sequence numbers assigned at send time
        against the current actor instance (so a restarted actor starts at
        seq 0), replies handled asynchronously. A task in flight when the
        actor dies fails — actor tasks are never implicitly re-executed
        (reference: max_task_retries defaults to 0 for actors)."""
        try:
            while st["queue"]:
                spec = st["queue"][0]
                try:
                    addr, restarts = await self._resolve_actor(actor_id)
                except ActorDiedError as e:
                    while st["queue"]:
                        self._store_task_error(st["queue"].popleft(), e)
                    return
                if not st["queue"] or st["queue"][0] is not spec:
                    # cancel dequeued the head while we awaited
                    # _resolve_actor: the cancelled spec must not be sent,
                    # and whatever is at the head now must not be dropped.
                    continue
                st["queue"].popleft()
                self._assign_seq(st, addr, restarts, spec)
                asyncio.ensure_future(self._push_actor_task(st, spec, addr))
        finally:
            st["sending"] = False

    async def _push_actor_task(self, st: dict, spec: task_mod.TaskSpec,
                               addr: str):
        try:
            worker = await self._clients.get(addr)
            self._inflight_tasks[spec.task_id] = addr
            reply = await worker.call("push_task", {"spec": spec.to_wire()},
                                      timeout=None)
            self._inflight_tasks.pop(spec.task_id, None)
            self._process_task_reply(spec, reply)
        except (ConnectionLost, RpcError, OSError) as e:
            self._actor_task_failed(st, spec, addr, e)

    async def _resolve_actor(self, actor_id: bytes,
                             timeout: float | None = None
                             ) -> Tuple[str, int]:
        st = self._actor_state(actor_id)
        if st.get("instance") is not None:
            return st["instance"]
        deadline = None if timeout is None else self._loop.time() + timeout
        while True:
            reply = await self.gcs.call("get_actor", {"actor_id": actor_id})
            if reply.get("found"):
                if reply["state"] == "ALIVE":
                    st["instance"] = (reply["addr"],
                                      reply.get("num_restarts", 0))
                    return st["instance"]
                if reply["state"] == "DEAD":
                    raise ActorDiedError(
                        f"actor {actor_id.hex()[:8]} is dead: "
                        f"{reply.get('death_cause')}"
                    )
            ev = self._actor_events.setdefault(actor_id, asyncio.Event())
            ev.clear()
            t = 1.0
            if deadline is not None:
                t = min(t, max(0.05, deadline - self._loop.time()))
                if self._loop.time() > deadline:
                    raise ActorDiedError(
                        f"timed out resolving actor {actor_id.hex()[:8]}")
            try:
                await asyncio.wait_for(ev.wait(), t)
            except asyncio.TimeoutError:
                pass

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._run_sync(self.gcs.call("kill_actor", {
            "actor_id": actor_id.binary(),
            "reason": "ray_tpu.kill",
        }))

    # ------------------------------------------------------------------
    # task cancellation (reference: ray.cancel, worker.py:2932;
    # CancelTask/RemoteCancelTask, core_worker.proto:252-270)
    # ------------------------------------------------------------------

    def cancel(self, ref, force: bool = False, recursive: bool = True):
        """Best-effort cancel of the task that produces `ref`: pending
        tasks are dequeued and error with TaskCancelledError; running
        tasks are interrupted at the executor (async actor tasks via
        coroutine cancel, sync tasks via an async thread exception);
        `force` kills the executing worker process; `recursive` also
        cancels the task's unfinished children."""
        if isinstance(ref, ObjectRefGenerator):
            ref.close()
            return
        # return ids embed the producing task id in their first 13 bytes
        # (ids.ObjectID.for_task_return)
        task_id = ref.binary()[:13] + b"\x00\x00\x00"
        self._run_sync(self._cancel_task_async(task_id, force, recursive))

    async def _cancel_task_async(self, task_id: bytes, force: bool,
                                 recursive: bool) -> bool:
        self._prune_cancel_ids(self._cancelled_tasks)
        self._cancelled_tasks[task_id] = time.monotonic()
        err = TaskCancelledError("task was cancelled")
        # pending in a normal-task submit queue: dequeue + error
        for state in self._key_states.values():
            for entry in list(state.queue):
                if entry[0].task_id == task_id:
                    try:
                        state.queue.remove(entry)
                    except ValueError:
                        continue  # a drain loop claimed it first
                    self._store_task_error(entry[0], err)
                    return True
        # pending in an actor send queue
        for st in self._actor_clients.values():
            for spec in list(st["queue"]):
                if spec.task_id == task_id:
                    try:
                        st["queue"].remove(spec)
                    except ValueError:
                        continue
                    self._store_task_error(spec, err)
                    return True
        # pushed: ask the worker it is executing on (or queued at)
        addr = self._inflight_tasks.get(task_id)
        if addr is not None:
            try:
                w = await self._clients.get(addr)
                await w.call("cancel_task", {
                    "task_id": task_id, "force": force,
                    "recursive": recursive,
                }, timeout=10.0)
                return True
            except (ConnectionLost, RpcError, OSError,
                    asyncio.TimeoutError):
                return False
        return False

    # ------------------------------------------------------------------
    # owner services (RPC handlers, run on io loop)
    # ------------------------------------------------------------------

    async def rpc_get_object_status(self, req):
        oid = req["object_id"]
        mem = self.memory_store
        if oid in self._freed_objects and not mem.ready(oid):
            # freed on refcount zero: a borrower whose add_borrower
            # lost the race with the final deref must error out now —
            # waiting would hang forever on books we emptied
            return {"status": "err", "value": serialization.dumps(
                ObjectLostError(
                    f"object {oid.hex()[:12]} was freed by its owner "
                    "(refcount reached zero before this borrow was "
                    "registered)"))}
        if req.get("wait") and not mem.ready(oid):
            if self.store is not None and self.store.contains(ObjectID(oid)):
                mem.add_location(oid, self.raylet_addr)
            else:
                await mem.wait_ready(oid)
        if oid in mem.errors:
            return {"status": "err", "value": mem.errors[oid]}
        if oid in mem.values:
            return {"status": "inband", "value": mem.values[oid]}
        return {"status": "plasma", "locations": mem.locations.get(oid, [])}

    async def rpc_add_object_location(self, req):
        self.memory_store.add_location(req["object_id"], req["raylet_addr"])
        return {"ok": True}

    async def rpc_pubsub(self, msg):
        if msg["channel"] == "actors":
            data = msg["data"]
            actor_id = data["actor_id"]
            st = self._actor_state(actor_id)
            if data["state"] == "ALIVE":
                st["instance"] = (data["addr"], data.get("num_restarts", 0))
            else:
                st["instance"] = None
            ev = self._actor_events.get(actor_id)
            if ev is not None:
                ev.set()
        elif msg["channel"] == "nodes":
            data = msg["data"]
            if data.get("event") == "removed":
                await self._on_node_removed(data)
        return None

    async def _on_node_removed(self, data: dict):
        """GCS death notice: invalidate every advertised location on the
        dead node and recover — or fail fast — owned objects whose last
        copy died with it (reference: ObjectRecoveryManager's node-death
        path). Runs on the io loop, so the location scan is atomic with
        respect to reply processing."""
        dead_addr = data.get("raylet_addr", "")
        if not dead_addr:
            return  # pre-recovery GCS build: notice carries no address
        # dead peers leave the client pool so reconnect backoff cannot
        # stall lease rerouting; mark_dead makes any later dial (a
        # lease spilled back to the victim by a raylet that hasn't seen
        # the death yet, an unpin, a status probe) fail fast instead of
        # burning a full connect timeout against a black hole
        self._clients.invalidate(dead_addr)
        self._clients.mark_dead(dead_addr)
        mem = self.memory_store
        lost: List[bytes] = []
        for oid in list(mem.locations.keys()):
            locs = mem.locations.get(oid)
            if not locs or dead_addr not in locs:
                continue
            mem.drop_location(oid, dead_addr)
            if oid not in mem.locations and oid not in mem.values \
                    and oid not in mem.errors:
                lost.append(oid)
        for oid, addr in list(self._pinned_at.items()):
            if addr == dead_addr:
                # the pin died with the raylet holding it
                self._pinned_at.pop(oid, None)
        for oid in lost:
            if oid in self._lineage_oids:
                asyncio.ensure_future(self._reconstruct(oid))
            else:
                self._fail_lost_object(oid)

    async def rpc_add_borrower(self, req):
        """A worker deserialized a ref we own: hold the object until it
        reports release (reference: the borrower half of
        WaitForRefRemoved, inverted to borrower-push)."""
        oid = req["object_id"]
        with self._ref_lock:
            self._borrowers.setdefault(oid, set()).add(req["addr"])
        return {"ok": True}

    async def rpc_remove_borrower(self, req):
        oid = req["object_id"]
        release = False
        with self._ref_lock:
            s = self._borrowers.get(oid)
            if s is not None:
                s.discard(req["addr"])
                if not s:
                    self._borrowers.pop(oid, None)
            release = self._ref_gone(oid)
        if release:
            self._on_ref_released(oid)
        return {"ok": True}

    async def rpc_dump_stacks(self, req):
        """All Python thread stacks of this worker/driver process for
        `ray_tpu stack`. Served from the RPC loop thread, so a task
        wedging the executor thread still gets its stack reported —
        which is the whole point of asking."""
        from ray_tpu._private import health as health_mod

        return {"pid": os.getpid(), "role": "worker",
                "worker_id": self.worker_id.binary().hex(),
                "threads": health_mod.dump_stacks()}

    async def rpc_exit_worker(self, req):
        logger.info("exit requested: %s", req.get("reason"))
        self._exec_queue.put(None)
        return None

    async def rpc_cancel_task(self, req):
        """Executor side of ray_tpu.cancel (reference: RemoteCancelTask,
        core_worker.proto:261). Marks the id so a not-yet-started task
        errors at dispatch; interrupts a running one (coroutine cancel
        for async actors, async thread exception for sync executors);
        recursively cancels the task's children; `force` exits the
        worker process."""
        task_id = req["task_id"]
        force = req.get("force", False)
        recursive = req.get("recursive", True)
        self._prune_cancel_ids(self._cancel_requested)
        self._cancel_requested[task_id] = time.monotonic()
        atask = self._running_async.get(task_id)
        if atask is not None and self._actor_async_loop is not None:
            self._actor_async_loop.call_soon_threadsafe(atask.cancel)
        else:
            tid = self._running_threads.get(task_id)
            if tid is not None:
                import ctypes

                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid),
                    ctypes.py_object(_TaskCancelledInterrupt))
        child_cancels = []
        if recursive:
            # this worker OWNS the children the task submitted — cancel
            # them through its own submitter machinery
            for child in list(self._task_children.get(task_id, ())):
                child_cancels.append(asyncio.ensure_future(
                    self._cancel_task_async(child, force, recursive)))
        if force:
            # Reply first, then die: the owner maps the connection loss
            # to TaskCancelledError via its cancelled set. The exit must
            # WAIT for the child-cancel RPCs — this process is those
            # children's owner; dying before the cancels reach their
            # executors would orphan them running to completion.
            async def _die():
                if child_cancels:
                    await asyncio.wait(child_cancels, timeout=5.0)
                await asyncio.sleep(0.1)  # let the reply frame flush
                os._exit(1)

            asyncio.ensure_future(_die())
        return {"ok": True}

    # ------------------------------------------------------------------
    # task execution (worker mode; reference: _raylet.pyx execute_task)
    # ------------------------------------------------------------------

    async def rpc_push_task(self, req):
        spec = task_mod.TaskSpec.from_wire(req["spec"])
        loop = self._loop
        fut = loop.create_future()
        if spec.task_type == task_mod.ACTOR_TASK:
            await self._enqueue_ordered(spec, fut)
        else:
            self._exec_queue.put((spec, fut))
        return await fut

    async def rpc_push_task_batch(self, req):
        """Executor side of the coalesced submit: one frame, many tasks.
        All are enqueued before the first reply is awaited, and the one
        reply frame carries every result (submitter batches replies back
        out to per-task processing). Tasks bound for the serial executor
        (normal tasks; sync actors without concurrency machinery) ride
        ONE executor hop and post all their results in ONE threadsafe
        callback — per-task thread wakeups would dominate small-task
        batches."""
        futs = []
        serial: list = []  # (spec, fut) executed back-to-back
        for wire in req["specs"]:
            spec = task_mod.TaskSpec.from_wire(wire)
            fut = self._loop.create_future()
            futs.append(fut)
            if spec.task_type == task_mod.ACTOR_TASK:
                for pair in self._enqueue_ordered_collect(spec, fut):
                    if self._serial_executable(pair[0]):
                        serial.append(pair)
                    else:
                        self._dispatch_actor_task(*pair)
            else:
                serial.append((spec, fut))
        if len(serial) == 1:
            spec, fut = serial[0]
            if spec.task_type == task_mod.ACTOR_TASK:
                self._dispatch_actor_task(spec, fut)
            else:
                self._exec_queue.put((spec, fut))
        elif serial:
            self._exec_queue.put((serial, None))
        return await asyncio.gather(*futs)

    def _serial_executable(self, spec: task_mod.TaskSpec) -> bool:
        """True when this actor task would land on the worker main
        thread anyway (no async loop, no threadpool, no concurrency
        groups) — the only case batch execution cannot reduce
        parallelism."""
        return (self._actor_async_loop is None
                and self._actor_threadpool is None
                and not self._actor_group_pools
                and not self._resolve_group(spec))

    async def _enqueue_ordered(self, spec: task_mod.TaskSpec, fut):
        for pair in self._enqueue_ordered_collect(spec, fut):
            self._dispatch_actor_task(*pair)

    def _enqueue_ordered_collect(self, spec: task_mod.TaskSpec, fut):
        """Per-caller (epoch, seq) ordering (reference: ActorSchedulingQueue).

        The epoch bumps when the caller restarts numbering (reconnect after a
        connection loss, or actor restart). A newer epoch means no more
        frames from the old one can arrive: flush whatever is buffered (best
        effort, in seq order — the missing seqs died with the connection)
        and resync at seq 0. An older epoch is a stray orphan; run it rather
        than wedge the stream."""
        caller = spec.owner_worker_id
        ready: list = []
        st = self._actor_seq_state.get(caller)
        if st is None:
            st = self._actor_seq_state[caller] = {
                "epoch": -1, "expect": 0, "buffer": {},
            }
        if spec.seq_epoch < st["epoch"]:
            ready.append((spec, fut))
            return ready
        if spec.seq_epoch > st["epoch"]:
            for seq in sorted(st["buffer"]):
                ready.append(st["buffer"][seq])
            st["buffer"] = {}
            st["epoch"] = spec.seq_epoch
            st["expect"] = 0
        st["buffer"][spec.seq_no] = (spec, fut)
        while st["expect"] in st["buffer"]:
            ready.append(st["buffer"].pop(st["expect"]))
            st["expect"] += 1
        return ready

    def _dispatch_actor_task(self, spec, fut):
        if self._actor_async_loop is not None:
            asyncio.run_coroutine_threadsafe(
                self._run_async_actor_task(spec, fut), self._actor_async_loop
            )
            return
        if spec.task_type == task_mod.ACTOR_TASK:
            group = self._resolve_group(spec)
            pools = self._actor_group_pools or {}
            pool = pools.get(group)
            if group and pool is None:
                # an explicitly-requested group must exist — silently
                # running in the default executor would void the
                # caller's isolation assumption (same contract as the
                # async path)
                reply = self._group_error(spec, group)
                self._loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(reply))
                return
            if pool is not None:
                pool.submit(self._execute_to_future, spec, fut)
                return
        if self._actor_threadpool is not None:
            self._actor_threadpool.submit(self._execute_to_future, spec, fut)
        else:
            self._exec_queue.put((spec, fut))

    def run_task_loop(self):
        """Blocks forever executing tasks (worker main thread). Queue
        items are (spec, fut) singles or ([(spec, fut), ...], None)
        batches from rpc_push_task_batch."""
        while True:
            item = None
            try:
                item = self._exec_queue.get()
                if item is None:
                    break
                spec, fut = item
                if isinstance(spec, list):
                    self._execute_batch(spec)
                else:
                    self._execute_to_future(spec, fut)
            except _TaskCancelledInterrupt:
                # A cancel interrupt that landed between tasks (the
                # target already finished): the loop must survive it,
                # and the in-hand item's reply futures must still
                # resolve — a dropped item would strand its owner's
                # get() forever. item None means the interrupt consumed
                # the shutdown sentinel (or beat the store of a popped
                # item — vanishingly rare): exit rather than risk
                # blocking on get() forever after a lost sentinel.
                if item is None:
                    break
                self._resolve_lost_item(item)
                continue

    def _resolve_lost_item(self, item) -> None:
        spec, fut = item
        pairs = spec if isinstance(spec, list) else [(spec, fut)]
        replies = []
        for s, f in pairs:
            if s.task_id in self._cancel_requested:
                replies.append((f, self._package_cancelled(s)))
            else:
                try:
                    raise RayTaskError(
                        "task interrupted by a stale cancellation")
                except RayTaskError as e:
                    replies.append((f, self._package_error(s, e)))

        def post():
            for f, reply in replies:
                if not f.done():
                    f.set_result(reply)

        self._loop.call_soon_threadsafe(post)

    def _execute_guarded(self, spec) -> dict:
        """execute_task plus a net for cancel interrupts that land in
        the gaps outside its own try block — a reply is ALWAYS produced
        (a swallowed interrupt would strand the owner's future)."""
        try:
            return self.execute_task(spec)
        except _TaskCancelledInterrupt:
            if spec.task_id in self._cancel_requested:
                return self._package_cancelled(spec)
            try:
                raise RayTaskError(
                    "task interrupted by a stale cancellation")
            except RayTaskError as e:
                return self._package_error(spec, e)

    def _execute_to_future(self, spec, fut):
        reply = self._execute_guarded(spec)
        self._loop.call_soon_threadsafe(
            lambda: fut.done() or fut.set_result(reply)
        )

    def _execute_batch(self, pairs):
        """Execute a batch serially, then resolve every reply future in
        ONE loop callback (one self-pipe write instead of len(pairs)).
        A cancel interrupt landing BETWEEN tasks of the batch must not
        discard batchmates: completed results stand, the in-hand spec
        gets a cancelled reply only if it was the cancel target, and
        everything else resumes execution (a stale interrupt — its
        target already finished — is simply consumed)."""
        results = []
        i = 0
        while i < len(pairs):
            spec, fut = pairs[i]
            try:
                results.append((fut, self._execute_guarded(spec)))
                i += 1
            except _TaskCancelledInterrupt:
                if spec.task_id in self._cancel_requested:
                    results.append((fut, self._package_cancelled(spec)))
                    i += 1
                # else: stale interrupt aimed at an already-finished
                # batchmate; retry the in-hand spec. (The only
                # double-execution window is the few bytecodes between
                # _execute_guarded returning and append — acceptable
                # for a best-effort cancel, same as reference.)

        def post():
            for fut, reply in results:
                if not fut.done():
                    fut.set_result(reply)

        self._loop.call_soon_threadsafe(post)

    async def _run_async_actor_task(self, spec, fut):
        self._running_async[spec.task_id] = asyncio.current_task()
        _executing_task_id.set(spec.task_id)  # task-local context
        try:
            if spec.task_id in self._cancel_requested:
                reply = self._package_cancelled(spec)
            else:
                group = self._resolve_group(spec) \
                    if spec.task_type == task_mod.ACTOR_TASK else ""
                sems = self._actor_group_sems
                if group and group not in sems:
                    reply = self._group_error(spec, group)
                else:
                    sem = sems.get(group, self._actor_async_sem)
                    async with sem:
                        reply = await self._execute_task_async(spec)
        except asyncio.CancelledError:
            # ray_tpu.cancel on a running async actor task: catching the
            # cancellation (not re-raising) lets the reply flow back
            reply = self._package_cancelled(spec)
        finally:
            self._running_async.pop(spec.task_id, None)
            self._task_children.pop(spec.task_id, None)
            self._cancel_requested.pop(spec.task_id, None)
        self._loop.call_soon_threadsafe(
            lambda: fut.done() or fut.set_result(reply)
        )

    async def _execute_task_async(self, spec: task_mod.TaskSpec):
        with tracing.execute_span(spec):
            return await self._execute_task_async_inner(spec)

    async def _execute_task_async_inner(self, spec: task_mod.TaskSpec):
        try:
            if self._args_all_inline(spec):
                args, kwargs = self._deserialize_inline_args(spec)
            else:
                args, kwargs = await asyncio.wrap_future(
                    asyncio.run_coroutine_threadsafe(
                        self._deserialize_args(spec), self._loop
                    )
                )
            method = getattr(self._actor_instance, spec.method_name)
            result = method(*args, **kwargs)
            if asyncio.iscoroutine(result) and \
                    not inspect.isgenerator(result):
                # isgenerator guard: on py<3.12 asyncio.iscoroutine also
                # matches plain generators, and awaiting one TypeErrors
                # instead of reaching the streaming dispatch below
                result = await result
            if spec.streaming and hasattr(result, "__anext__"):
                return await self._execute_streaming_async(spec, result)
            if spec.streaming and hasattr(result, "__next__"):
                # sync generator on an async actor: drive it off-loop —
                # the per-item ack waits (backpressure) must not freeze
                # the actor's other coroutines
                return await asyncio.get_running_loop().run_in_executor(
                    None, self._execute_streaming, spec, result)
            # packaging can block (plasma write + pin RPC under memory
            # pressure) — keep it off the actor's event loop
            return await asyncio.get_running_loop().run_in_executor(
                None, self._package_returns, spec, result)
        except Exception as e:  # noqa: BLE001
            return self._package_error(spec, e)

    def execute_task(self, spec: task_mod.TaskSpec) -> dict:
        with tracing.execute_span(spec):
            return self._execute_task_inner(spec)

    @staticmethod
    def _prune_cancel_ids(d: Dict[bytes, float], max_age: float = 600.0,
                          soft_cap: int = 1024) -> None:
        """Bound the cancel-id books: ids normally leave at the task's
        terminal reply, but a cancel aimed at an already-finished task
        has no terminal event — age the stragglers out."""
        if len(d) <= soft_cap:
            return
        cutoff = time.monotonic() - max_age
        for k in [k for k, ts in d.items() if ts < cutoff]:
            del d[k]

    def _package_cancelled(self, spec: task_mod.TaskSpec) -> dict:
        try:
            raise TaskCancelledError("task was cancelled")
        except TaskCancelledError as e:
            return self._package_error(spec, e)

    def _execute_task_inner(self, spec: task_mod.TaskSpec) -> dict:
        if spec.task_id in self._cancel_requested:
            return self._package_cancelled(spec)  # cancelled while queued
        prev_task = self.current_task_id
        self.current_task_id = TaskID(spec.task_id)
        self._running_threads[spec.task_id] = threading.get_ident()
        ctx_token = _executing_task_id.set(spec.task_id)
        try:
            # All-inline args decode right here; only by-reference args
            # need the event loop's async resolution machinery (two
            # thread hops per task — measurable on small tasks).
            if self._args_all_inline(spec):
                args, kwargs = self._deserialize_inline_args(spec)
            else:
                args, kwargs = asyncio.run_coroutine_threadsafe(
                    self._deserialize_args(spec), self._loop
                ).result()
            if spec.task_type == task_mod.NORMAL_TASK:
                fn = self._function_cache.get(spec.function_key)
                if fn is None:
                    fn = asyncio.run_coroutine_threadsafe(
                        self._load_function(spec.function_key), self._loop
                    ).result()
                result = fn(*args, **kwargs)
            elif spec.task_type == task_mod.ACTOR_CREATION_TASK:
                cls = asyncio.run_coroutine_threadsafe(
                    self._load_function(spec.function_key), self._loop
                ).result()
                instance = cls(*args, **kwargs)
                self._actor_instance = instance
                self.current_actor_id = ActorID(spec.actor_id)
                groups = spec.concurrency_groups
                if self._has_async_methods(cls):
                    if spec.max_concurrency > 1 or groups:
                        self._start_actor_async_loop(
                            max(1, spec.max_concurrency), groups)
                    else:
                        self._start_actor_async_loop(1)
                elif groups:
                    # named concurrency groups, threaded actor
                    # (reference: concurrency_group_manager.h — one
                    # executor per group + the default group)
                    self._actor_group_pools = {
                        name: ThreadPoolExecutor(
                            max(1, int(n)),
                            thread_name_prefix=f"group-{name}")
                        for name, n in groups.items()
                    }
                    self._actor_threadpool = ThreadPoolExecutor(
                        max(1, spec.max_concurrency),
                        thread_name_prefix="group-default")
                elif spec.max_concurrency > 1:
                    self._actor_threadpool = ThreadPoolExecutor(
                        spec.max_concurrency
                    )
                return {"returns": []}
            elif spec.task_type == task_mod.ACTOR_TASK:
                if spec.method_name == "__ray_tpu_channel_graph__":
                    # compiled-DAG channel stages (reference: the aDAG
                    # executor loop, compiled_dag_node.py): starts a
                    # daemon thread pumping this actor's graph nodes —
                    # read input channels, run method, write output
                    # channels — so the actor stays callable
                    result = self._start_channel_graph(*args, **kwargs)
                else:
                    method = getattr(self._actor_instance,
                                     spec.method_name)
                    result = method(*args, **kwargs)
                if asyncio.iscoroutine(result) and \
                        not inspect.isgenerator(result):
                    # Sync path got a coroutine (async method, concurrency 1
                    # without dedicated loop): run it to completion here.
                    # The isgenerator guard matters on py<3.12, where
                    # asyncio.iscoroutine also matches plain generators
                    # (legacy @asyncio.coroutine) — asyncio.run on a
                    # streaming task's generator raises "Task got bad
                    # yield" instead of streaming it.
                    result = asyncio.run(result)
            else:
                raise RuntimeError(f"unknown task type {spec.task_type}")
            return self._package_returns(spec, result)
        except _TaskCancelledInterrupt:
            if spec.task_id in self._cancel_requested:
                return self._package_cancelled(spec)
            # stale interrupt aimed at a prior task landed here (the
            # SetAsyncExc race window): report honestly, not as a
            # cancellation of THIS task
            try:
                raise RayTaskError(
                    "task interrupted by a stale cancellation aimed at "
                    "a previously-running task")
            except RayTaskError as e:
                return self._package_error(spec, e)
        except Exception as e:  # noqa: BLE001
            return self._package_error(spec, e)
        finally:
            self.current_task_id = prev_task
            _executing_task_id.reset(ctx_token)
            self._running_threads.pop(spec.task_id, None)
            self._task_children.pop(spec.task_id, None)
            self._cancel_requested.pop(spec.task_id, None)

    def _start_channel_graph(self, stages: list) -> str:
        """Compiled-DAG stage executor (reference: the per-actor loop a
        compiled graph installs, `compiled_dag_node.py:291`; channel
        design `experimental_mutable_object_manager.h:37`): attach every
        stage's in/out shm channels NOW (so a wrong-node placement fails
        the compile call loudly), then pump this actor's nodes in
        topological order on one daemon thread — fan-in reads one
        channel per argument, fan-out writes one channel per consumer.
        Frames carry a raw (tag, seq, length) header + pickled payload
        (zero-pickle plane, ray_tpu/experimental/channel.py); an
        upstream error flows through untouched so the driver sees the
        original, and lagging inputs are released from the header alone
        — never deserialized — and re-read until their seqs agree
        (self-healing after a driver-side timeout)."""
        import pickle

        from ray_tpu.experimental.channel import (TAG_ERR, TAG_OK,
                                                  ChannelClosedError,
                                                  FrameScratch,
                                                  ShmChannel,
                                                  note_stale_skip)

        attached: Dict[str, ShmChannel] = {}

        def get_ch(name: str) -> ShmChannel:
            if name not in attached:
                attached[name] = ShmChannel.attach(name)
            return attached[name]

        prepared = []
        for st in stages:
            prepared.append((
                st,
                [(pos, get_ch(n)) for pos, n in st["ins"]],
                [get_ch(n) for n in st["outs"]],
                getattr(self._actor_instance, st["method"]),
                FrameScratch(),
            ))

        def run_stage(st, ins, outs, method, scratch):
            chans = dict(ins)
            # headers first: (tag, seq, payload_view) per input, slots
            # still held — nothing deserialized yet
            heads = {pos: ch.read_frame() for pos, ch in ins}
            while True:
                mx = max(s for (_t, s, _v) in heads.values())
                lagging = [p for p, (_t, s, _v) in heads.items()
                           if s < mx]
                if not lagging:
                    break
                for p in lagging:
                    # stale frame: release straight from the header —
                    # the payload is never unpickled just to be thrown
                    # away
                    heads[p] = None  # drop the payload view first
                    chans[p].release_frame()
                    note_stale_skip()
                    heads[p] = chans[p].read_frame()
            traced = tracing.enabled()
            if traced:
                # consumer half of each input hop's arrow: the frame
                # header carries no trace ctx, so the producer span
                # (driver/upstream stage) and this span share
                # flow_id=<channel>:<seq> and the unified timeline
                # stitches the cross-process arrow at merge time
                for _pos, ch in ins:
                    with tracing.span(
                            "channel.read", kind="consumer",
                            attrs={"channel": ch._name, "seq": mx,
                                   "flow_id": f"{ch._name}:{mx}"}):
                        pass
            err = None
            values = {}
            for pos, (tag, _s, view) in heads.items():
                if tag == TAG_ERR:
                    if err is None:
                        err = pickle.loads(view)
                else:
                    values[pos] = pickle.loads(view)
                del view
                heads[pos] = None
                chans[pos].release_frame()
            if err is not None:
                tag, view = TAG_ERR, scratch.pack(err)
            else:
                fn_args = [None] * st["nargs"]
                for pos, v in st["consts"]:
                    fn_args[pos] = v
                for pos, v in values.items():
                    fn_args[pos] = v
                try:
                    if traced:
                        with tracing.span(f"stage.{st['method']}",
                                          attrs={"seq": mx}):
                            result = method(*fn_args)
                    else:
                        result = method(*fn_args)
                    tag, view = TAG_OK, scratch.pack(result)
                except Exception as e:  # noqa: BLE001 — to driver
                    tag, view = TAG_ERR, scratch.pack(
                        f"{st['method']} failed: "
                        f"{traceback.format_exc()}\n{e!r}")
            for out in outs:
                try:
                    if traced:
                        with tracing.span(
                                "channel.write", kind="producer",
                                attrs={"channel": out._name, "seq": mx,
                                       "flow_id": f"{out._name}:{mx}"}):
                            out.write_frame(tag, mx, view)
                    else:
                        out.write_frame(tag, mx, view)
                except ValueError as e:
                    # oversize result: the pump must survive and the
                    # driver must see the cause (the tiny error frame
                    # always fits)
                    out.write_frame(TAG_ERR, mx, pickle.dumps(
                        f"{st['method']} result does not fit the "
                        f"channel: {e}"))

        def loop():
            try:
                while True:
                    for item in prepared:
                        run_stage(*item)
            except ChannelClosedError:
                pass
            finally:
                for ch in attached.values():
                    ch.close()

        threading.Thread(target=loop, daemon=True,
                         name="dag-graph").start()
        return "started"

    @staticmethod
    def _has_async_methods(cls) -> bool:
        import inspect as inspect_mod

        def is_async(fn):
            return (asyncio.iscoroutinefunction(fn)
                    or inspect_mod.isasyncgenfunction(fn))

        return any(
            is_async(getattr(cls, n, None))
            for n in dir(cls)
            if not n.startswith("__")
        )

    def _start_actor_async_loop(self, max_concurrency: int,
                                groups: Dict[str, int] | None = None):
        loop = asyncio.new_event_loop()
        self._actor_async_loop = loop
        self._actor_async_sem = asyncio.Semaphore(max_concurrency)
        # async actors: a named group is a semaphore on the shared loop
        # (the reference's fiber groups) — per-group admission, one loop
        self._actor_group_sems = {
            name: asyncio.Semaphore(max(1, int(n)))
            for name, n in (groups or {}).items()
        }

        def run():
            asyncio.set_event_loop(loop)
            loop.run_forever()

        threading.Thread(target=run, name="actor-async", daemon=True).start()

    def _group_error(self, spec: task_mod.TaskSpec, group: str) -> dict:
        declared = sorted((self._actor_group_pools
                           or self._actor_group_sems or {}).keys())
        # raise-and-catch: _package_error formats the ACTIVE exception
        try:
            raise ValueError(f"unknown concurrency group {group!r} "
                             f"(declared: {declared})")
        except ValueError as e:
            return self._package_error(spec, e)

    def _resolve_group(self, spec: task_mod.TaskSpec) -> str:
        """Task's group: explicit call-site override, else the method's
        declared group (@ray_tpu.method(concurrency_group=...)), else
        the default group ('')."""
        if spec.concurrency_group:
            return spec.concurrency_group
        m = getattr(type(self._actor_instance), spec.method_name or "",
                    None)
        return getattr(m, "__ray_tpu_concurrency_group__", "") or ""

    # -- executor-side streaming ------------------------------------------

    def _package_item(self, spec: task_mod.TaskSpec, index: int,
                      value) -> list:
        """Package one yielded item exactly like a return value: small
        in-band, large into plasma."""
        oid = ObjectID.for_task_return(TaskID(spec.task_id), index)
        sv = serialization.serialize_value(value)
        if sv.size <= self.config.max_direct_call_object_size or \
                self.store is None:
            return [oid.binary(), "v", sv.to_bytes()]
        self._plasma_put_pinned(oid, sv)
        return [oid.binary(), "plasma", self.raylet_addr]

    async def _report_item(self, spec: task_mod.TaskSpec, item: list) -> dict:
        owner = await self._clients.get(spec.owner_addr)
        return await owner.call("report_stream_item", {
            "task_id": spec.task_id, "item": item,
        }, timeout=None)

    def _execute_streaming(self, spec: task_mod.TaskSpec, gen) -> dict:
        """Drive a sync generator, reporting each item to the owner. The
        per-item ack is the backpressure gate (the owner withholds it
        while its buffer is full) and carries early-cancellation."""
        index = 0
        try:
            for value in gen:
                item = self._package_item(spec, index, value)
                index += 1
                ack = asyncio.run_coroutine_threadsafe(
                    self._report_item(spec, item), self._loop).result()
                if ack.get("cancelled"):
                    gen.close()
                    break
        except Exception:  # noqa: BLE001 — shipped to the consumer
            tb = traceback.format_exc()
            frame = serialization.dumps(RayTaskError(
                f"streaming task {spec.name} failed at item {index}:\n{tb}"))
            oid = ObjectID.for_task_return(TaskID(spec.task_id), index)
            asyncio.run_coroutine_threadsafe(
                self._report_item(spec, [oid.binary(), "err", frame]),
                self._loop).result()
        return {"returns": [], "stream_items": index}

    async def _execute_streaming_async(self, spec: task_mod.TaskSpec,
                                       agen) -> dict:
        """Async-actor variant: drives an async generator (Serve response
        streaming rides on this path)."""
        index = 0
        loop = asyncio.get_running_loop()
        try:
            async for value in agen:
                item = await loop.run_in_executor(
                    None, self._package_item, spec, index, value)
                index += 1
                ack = await asyncio.wrap_future(
                    asyncio.run_coroutine_threadsafe(
                        self._report_item(spec, item), self._loop))
                if ack.get("cancelled"):
                    await agen.aclose()
                    break
        except Exception:  # noqa: BLE001
            tb = traceback.format_exc()
            frame = serialization.dumps(RayTaskError(
                f"streaming task {spec.name} failed at item {index}:\n{tb}"))
            oid = ObjectID.for_task_return(TaskID(spec.task_id), index)
            await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self._report_item(spec, [oid.binary(), "err", frame]),
                self._loop))
        return {"returns": [], "stream_items": index}

    def _package_returns(self, spec: task_mod.TaskSpec, result) -> dict:
        if spec.streaming:
            if not hasattr(result, "__next__"):
                raise TypeError(
                    f"streaming task {spec.name} must return a generator, "
                    f"got {type(result).__name__}")
            return self._execute_streaming(spec, result)
        if spec.num_returns == 0:
            return {"returns": []}
        if spec.num_returns == 1:
            results = [result]
        else:
            results = list(result)
            if len(results) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} returned {len(results)} values, "
                    f"expected {spec.num_returns}"
                )
        returns = []
        handoffs = []
        for i, value in enumerate(results):
            oid = ObjectID.for_task_return(TaskID(spec.task_id), i)
            sv, nested = serialization.serialize_value_with_refs(value)
            if nested:
                handoffs.append([
                    oid.binary(),
                    self._handoff_nested_refs(nested, spec.owner_addr)])
            if sv.size <= self.config.max_direct_call_object_size or \
                    self.store is None:
                returns.append([oid.binary(), "v", sv.to_bytes()])
            else:
                self._plasma_put_pinned(oid, sv)
                returns.append([oid.binary(), "plasma", self.raylet_addr])
        out = {"returns": returns}
        if handoffs:
            out["ref_handoffs"] = handoffs
        return out

    def _handoff_nested_refs(self, refs: list, caller_addr: str) -> list:
        """A return value carries ObjectRefs (executor thread): register
        the CALLER as a borrower with each ref's owner BEFORE the reply
        ships. Without this, the owner can free the object in the window
        between this task's locals dying (our borrow releases) and the
        caller deserializing its copy (its borrow registers) — the
        handoff makes the transfer of the reference atomic with the
        reply. Returns [(oid, owner_addr)] for the reply's
        `ref_handoffs` entry; the caller holds each pair until the
        return object itself is released."""
        pairs = []
        for r in refs:
            oid = r.binary()
            owner = r.owner_addr or self.address
            pairs.append([oid, owner])
            if owner == self.address:
                # we own it — the caller's borrow is one set-add away,
                # and our live handle (inside the return value) keeps
                # the refcount nonzero until this line runs
                with self._ref_lock:
                    self._borrowers.setdefault(oid, set()).add(caller_addr)
            else:
                # registered synchronously so the reply cannot overtake
                # it; covers owner == caller too (an object riding back
                # to its owner — the entry pins it against a racing
                # remove_borrower from our own task-end cleanup)
                fut = asyncio.run_coroutine_threadsafe(
                    self._notify_borrow(owner, "add_borrower", oid,
                                        addr=caller_addr), self._loop)
                try:
                    fut.result(timeout=30.0)
                except Exception:  # noqa: BLE001 — owner gone
                    pass
        return pairs

    def _package_error(self, spec: task_mod.TaskSpec, exc: Exception) -> dict:
        tb = traceback.format_exc()
        logger.warning("task %s failed: %s", spec.name, tb)
        # preserve framework error subtypes (TaskCancelledError etc.) so
        # the owner can re-raise the exact class the API promises
        cls = type(exc) if isinstance(exc, RayTaskError) else RayTaskError
        err = cls(f"task {spec.name} failed:\n{tb}", cause=None)
        frame = serialization.dumps(err)
        returns = []
        for i in range(max(spec.num_returns, 1)):
            oid = ObjectID.for_task_return(TaskID(spec.task_id), i)
            returns.append([oid.binary(), "err", frame])
        return {"returns": returns, "error": True, "error_msg": str(exc)}
