"""Deadman watchdog plane: liveness proof for the system's hot loops.

Reference: the reference's internal health checks + `ray stack` (cross
-process Python stack dumps). Passive telemetry (metrics, tsdb) tells
you a rate dropped to zero; it cannot tell a *quiet* loop from a
*wedged* one. This module closes that gap with the cheapest possible
instrument: every hot loop (raylet dispatch drain, serve router wake
loop, LLMEngine pump thread, GCS persist executors, soak driver)
registers a :class:`LoopProbe` and calls ``probe.beat()`` once per
iteration — one integer increment, no lock, no syscall. A per-daemon
:class:`Watchdog` thread then applies the deadman rule: a loop whose
beat counter is FROZEN while its backlog probe says there is work is
stalled. On detection it captures the culprit thread's stack via
``sys._current_frames()`` (plus held-lock info when lockdep is armed),
emits a ``health.stalled`` structured event, and flips the
``health_loop_stalled{loop=}`` gauge that the SLO alert plane watches.

Design rule (enforced by raylint's ``watchdog-probe`` checker): a beat
must NEVER be taken under the watched loop's lock. A watchdog whose
liveness signal requires the stalled lock can never fire — the probe
has to stay observable from outside the thing it observes.

``dump_stacks()`` is the per-process half of cluster-wide hang
diagnosis: the GCS, every raylet, and every core worker expose it as a
``dump_stacks`` RPC, aggregated by ``ray_tpu stack`` into one annotated
report (the distributed analog of ``ray stack``).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.util import events

# module-registry guard: a raw lock, never on any hot path (probes are
# registered once at loop start; beats never touch it)
_lock = threading.Lock()
_probes: Dict[str, "LoopProbe"] = {}
_metrics_registered = False


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class LoopProbe:
    """Monotonic progress counter for one hot loop.

    ``beat()`` is the only call on the hot path: an int increment plus a
    thread-ident store, both GIL-atomic — deliberately lock-free so the
    probe stays readable even when the watched loop's lock is wedged.
    ``backlog_fn`` answers "is there work this loop should be doing?"
    and is only called from the watchdog thread, at watchdog cadence.
    """

    __slots__ = ("name", "backlog_fn", "count", "thread_ident",
                 "stalled", "stalled_since", "stalls_total")

    def __init__(self, name: str,
                 backlog_fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.backlog_fn = backlog_fn
        self.count = 0
        self.thread_ident: Optional[int] = None
        self.stalled = False
        self.stalled_since: Optional[float] = None
        self.stalls_total = 0

    def beat(self) -> None:
        self.thread_ident = threading.get_ident()
        self.count += 1

    def backlog(self) -> float:
        if self.backlog_fn is None:
            return 0.0
        try:
            return float(self.backlog_fn())
        except Exception:  # noqa: BLE001 — probe must not take the loop down
            return 0.0


def watch_loop(name: str,
               backlog_fn: Optional[Callable[[], float]] = None
               ) -> LoopProbe:
    """Register (or re-register — restartable loops) a probe by name."""
    probe = LoopProbe(name, backlog_fn)
    with _lock:
        _probes[name] = probe
    _register_metrics()
    return probe


def loop_ticker(probe: LoopProbe, interval_s: float = 0.5):
    """Asyncio event-loop liveness ticker for a probe whose loop is
    event-driven rather than free-running (the raylet dispatch drain,
    the GCS handler plane): beats ride the loop itself, the backlog is
    the constant "next tick", so the deadman rule reads exactly
    'the event loop is blocked' — a legitimately quiet drain keeps
    beating, a sync call wedging a handler freezes the ticker along
    with every drain that shares the loop. Must be called from the
    running loop; returns the ticker task (cancel to stop)."""
    import asyncio

    if probe.backlog_fn is None:
        probe.backlog_fn = lambda: 1

    async def _tick():
        while True:
            probe.beat()
            await asyncio.sleep(interval_s)

    return asyncio.ensure_future(_tick())


def unwatch_loop(name: str) -> None:
    with _lock:
        _probes.pop(name, None)


def probes() -> List[LoopProbe]:
    with _lock:
        return list(_probes.values())


_watchdog_singleton: Optional["Watchdog"] = None


def ensure_watchdog(source: str = "HEALTH") -> "Watchdog":
    """Process-wide watchdog for components that live inside another
    process (an LLM engine in a replica actor, the soak driver in the
    test runner): first caller starts it, everyone shares it."""
    global _watchdog_singleton
    with _lock:
        if _watchdog_singleton is None:
            _watchdog_singleton = Watchdog(source=source).start()
        return _watchdog_singleton


def _reset_after_fork() -> None:
    """A forked child inherits probes whose threads don't exist in the
    child — every one would read as frozen. Start clean. Lockless on
    purpose: the inherited module lock may have been mid-acquire in
    the parent at fork time, and the child is single-threaded here."""
    global _watchdog_singleton
    _probes.clear()
    _watchdog_singleton = None  # raylint: disable=lock-discipline


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


# -- exposition ----------------------------------------------------------

def metrics_text() -> str:
    lines = ["# TYPE health_loop_beats_total counter"]
    snapshot = probes()
    for p in snapshot:
        lines.append(
            f'health_loop_beats_total{{loop="{p.name}"}} {p.count}')
    lines.append("# TYPE health_loop_stalled gauge")
    for p in snapshot:
        lines.append(
            f'health_loop_stalled{{loop="{p.name}"}} '
            f"{1 if p.stalled else 0}")
    lines.append("# TYPE health_loop_stalls_total counter")
    for p in snapshot:
        lines.append(
            f'health_loop_stalls_total{{loop="{p.name}"}} '
            f"{p.stalls_total}")
    lines.append("# TYPE health_stalled_loops gauge")
    lines.append(
        f"health_stalled_loops "
        f"{sum(1 for p in snapshot if p.stalled)}")
    return "\n".join(lines) + "\n"


def _register_metrics() -> None:
    global _metrics_registered
    if _metrics_registered:
        return
    try:
        from ray_tpu.util.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.register_callback("health", metrics_text)
        _metrics_registered = True
    except Exception:  # noqa: BLE001 — exposition is best-effort
        pass


# -- stack capture -------------------------------------------------------

def _format_stack(frame) -> str:
    return "".join(traceback.format_stack(frame))


def _held_locks_by_thread() -> Dict[int, List[str]]:
    """{thread_ident: [lock names]} when lockdep is armed, else {}."""
    try:
        from ray_tpu._private import lockdep

        if lockdep.enabled():
            return lockdep.held_locks()
    except Exception:  # noqa: BLE001 — diagnosis must not raise
        pass
    return {}


def dump_stacks(include_locks: bool = True) -> List[Dict[str, Any]]:
    """Every Python thread of this process: name, daemon flag, formatted
    stack, held tracked locks (lockdep), and — when the thread drives a
    registered loop probe — the probe's name and stall state. This is
    the payload of the `dump_stacks` RPC."""
    frames = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    held = _held_locks_by_thread() if include_locks else {}
    by_ident = {p.thread_ident: p for p in probes()
                if p.thread_ident is not None}
    out: List[Dict[str, Any]] = []
    for ident, frame in sorted(frames.items()):
        t = threads.get(ident)
        entry: Dict[str, Any] = {
            "ident": ident,
            "name": t.name if t is not None else f"thread-{ident}",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": _format_stack(frame),
        }
        if held.get(ident):
            entry["held_locks"] = held[ident]
        probe = by_ident.get(ident)
        if probe is not None:
            entry["loop"] = probe.name
            if probe.stalled:
                entry["stalled"] = True
        out.append(entry)
    return out


def capture_thread_stack(ident: Optional[int]) -> str:
    frame = sys._current_frames().get(ident) if ident else None
    return _format_stack(frame) if frame is not None else ""


# -- the watchdog --------------------------------------------------------

class Watchdog:
    """Per-daemon deadman checker (daemon thread, watchdog cadence).

    A probe is stalled when its beat counter has not moved for
    ``stall_s`` seconds while its backlog probe reports pending work —
    an idle loop (frozen counter, empty queue) is healthy. Detection
    captures the culprit thread's stack and emits ``health.stalled``;
    the first beat after that emits ``health.recovered``. State is
    observable through ``health_loop_stalled{loop=}`` which the SLO
    plane's deadman rule watches.
    """

    def __init__(self, source: str = "HEALTH",
                 interval_s: Optional[float] = None,
                 stall_s: Optional[float] = None):
        self.source = source
        self.interval_s = max(0.05, interval_s if interval_s is not None
                              else _env_float(
                                  "RAY_TPU_WATCHDOG_INTERVAL_S", 1.0))
        self.stall_s = max(0.1, stall_s if stall_s is not None
                           else _env_float(
                               "RAY_TPU_WATCHDOG_STALL_S", 5.0))
        self._seen: Dict[str, tuple] = {}  # name -> (count, ts)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.checks = 0
        _register_metrics()

    # split out so tests can drive the deadman rule synchronously
    def check_once(self, now: Optional[float] = None) -> List[str]:
        """One deadman sweep; returns the names of newly-stalled loops."""
        now = time.monotonic() if now is None else now
        self.checks += 1
        newly_stalled: List[str] = []
        for probe in probes():
            count = probe.count
            seen = self._seen.get(probe.name)
            if seen is None or count != seen[0]:
                self._seen[probe.name] = (count, now)
                if probe.stalled:
                    probe.stalled = False
                    stalled_for = (time.time() - probe.stalled_since
                                   if probe.stalled_since else 0.0)
                    probe.stalled_since = None
                    events.report(
                        self.source, "INFO", "health.recovered",
                        f"loop '{probe.name}' resumed after "
                        f"{stalled_for:.1f}s stall",
                        loop=probe.name, stalled_s=round(stalled_for, 3))
                continue
            frozen_s = now - seen[1]
            if probe.stalled or frozen_s < self.stall_s:
                continue
            backlog = probe.backlog()
            if backlog <= 0:
                continue  # idle, not stuck
            probe.stalled = True
            probe.stalled_since = time.time()
            probe.stalls_total += 1
            stack = capture_thread_stack(probe.thread_ident)
            held = _held_locks_by_thread().get(probe.thread_ident, [])
            events.report(
                self.source, "ERROR", "health.stalled",
                f"loop '{probe.name}' frozen for {frozen_s:.1f}s with "
                f"backlog {backlog:g}",
                loop=probe.name, frozen_s=round(frozen_s, 3),
                backlog=backlog, stack=stack, held_locks=held)
            newly_stalled.append(probe.name)
        return newly_stalled

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the watchdog never dies
                pass
            self._stop.wait(self.interval_s)

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="health-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
