"""Python binding for the native shared-memory object store.

The raylet creates one arena per node (`ObjectStore.create`); every worker on
the node attaches (`ObjectStore.attach`). Reads are zero-copy: Python mmaps
the same shm file the C++ library manages and returns memoryview slices over
the data region, so `get` of a numpy array is a view onto shared memory
(reference: plasma client `src/ray/object_manager/plasma/client.cc` +
`python/ray/_private/serialization.py` zero-copy reads).
"""

from __future__ import annotations

import mmap
import os
import sys

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu.native import load_shm_store

import ctypes

SS_OK = 0
SS_EXISTS = -1
SS_NOT_FOUND = -2
SS_NO_MEMORY = -3
SS_TABLE_FULL = -4
SS_TIMEOUT = -5
SS_NOT_SEALED = -6
SS_QUOTA = -9


class ObjectStoreError(Exception):
    pass


class ObjectStoreFullError(ObjectStoreError):
    pass


class ObjectTimeoutError(ObjectStoreError):
    pass


class QuotaExceededError(ObjectStoreError):
    """The creating job is at its per-job object-store byte quota and
    has no evictable objects of its own left to reclaim. Only the
    offending job sees this — other tenants' puts and objects are
    untouched (the quota sweep never crosses job boundaries)."""


def job_key(job_id_binary: bytes) -> int:
    """Fold a 16-byte JobID into the u64 accounting key the native
    store tracks. XOR of the two halves so small `JobID.from_int`
    values (big-endian, value in the tail) still map to nonzero keys;
    key 0 (the nil job) means untracked — v2 semantics, no quota."""
    a = int.from_bytes(job_id_binary[:8], "little")
    b = int.from_bytes(job_id_binary[8:16], "little")
    return a ^ b


class PlasmaBuffer:
    """Holds one store reference for the lifetime of its zero-copy views.

    Views are exported through the PEP-688 buffer protocol on 3.12+, so any
    memoryview slice (and any numpy array reconstructed from one by pickle5)
    keeps this object alive; when the last view is garbage-collected, __del__
    drops the store refcount and the object becomes evictable again. This
    mirrors the reference's plasma client Buffer semantics
    (src/ray/object_manager/plasma/client.cc — release-on-buffer-destruction).

    Interpreters older than 3.12 cannot export a buffer from pure Python
    (`__buffer__` is ignored and memoryview(self) raises TypeError), so
    `export()` re-exports the view through a ctypes array: the array pins the
    underlying view, derived memoryviews pin the array, and an attribute on
    the array pins this object — the same release-on-last-view lifetime.
    """

    __slots__ = ("_store", "_id_bytes", "_view", "__weakref__")

    def __init__(self, store: "ObjectStore", id_bytes: bytes, view: memoryview):
        self._store = store
        self._id_bytes = id_bytes
        self._view = view

    def __buffer__(self, flags: int) -> memoryview:
        return self._view

    def export(self) -> memoryview:
        """A memoryview over the object's bytes that holds the store ref."""
        if sys.version_info >= (3, 12):
            return memoryview(self)
        arr = (ctypes.c_char * self._view.nbytes).from_buffer(self._view)
        arr._plasma_ref = self  # released when the last derived view dies
        return memoryview(arr)

    @property
    def nbytes(self) -> int:
        return self._view.nbytes

    def __del__(self):
        store = self._store
        if store is None:
            return
        # snapshot: close() nulls _lib/_h BEFORE detaching, so a __del__
        # racing close()/destroy() either sees a live handle or a dead
        # store — never a detached handle index another attach may have
        # reused (which would corrupt the new store's refcounts)
        lib, h = store._lib, store._h
        if lib is not None and h >= 0:
            lib.ss_release(h, self._id_bytes)


class ObjectStore:
    def __init__(self, name: str, handle: int, lib):
        self._name = name
        self._lib = lib
        self._h = handle
        self._job_key = 0       # creator attribution for puts (0 = none)
        self._job_labels = {}   # job key -> short hex label for /metrics
        self._data_off = lib.ss_data_offset(handle)
        map_size = lib.ss_map_size(handle)
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            self._mmap = mmap.mmap(fd, map_size)
        finally:
            os.close(fd)
        self._view = memoryview(self._mmap)

    # -- lifecycle --------------------------------------------------------

    @classmethod
    def create(cls, name: str, capacity: int, table_size: int = 65536,
               shards: int = 0):
        """Create a store arena. `shards` picks the index/allocator
        stripe count (0 = scale with capacity: one stripe per 128 MB,
        capped at 16 — small test stores keep single-lock semantics).
        `RAY_TPU_STORE_SHARDS` overrides the default."""
        lib = load_shm_store()
        if shards == 0:
            shards = int(os.environ.get("RAY_TPU_STORE_SHARDS", "0"))
        h = lib.ss_create_store(name.encode(), capacity, table_size, shards)
        if h < 0:
            raise ObjectStoreError(f"failed to create store {name}: {h}")
        return cls(name, h, lib)

    @classmethod
    def attach(cls, name: str):
        lib = load_shm_store()
        h = lib.ss_attach(name.encode())
        if h < 0:
            raise ObjectStoreError(f"failed to attach store {name}: {h}")
        return cls(name, h, lib)

    def close(self):
        if self._h < 0:
            return
        lib, h = self._lib, self._h
        # Invalidate the handle BEFORE detaching: a late
        # PlasmaBuffer.__del__ (GC on another thread) must observe a
        # dead store rather than call ss_release on a handle index a
        # subsequent attach may have reused.
        self._h = -1
        self._lib = None
        lib.ss_detach(h)
        self._view.release()
        try:
            self._mmap.close()
        except BufferError:
            # Zero-copy views handed to callers still reference the
            # mapping; it is reclaimed when they are garbage-collected.
            pass

    def destroy(self):
        name, lib = self._name, self._lib
        self.close()
        if lib is None:  # already closed earlier; unlink still applies
            from ray_tpu.native import load_shm_store

            lib = load_shm_store()
        lib.ss_unlink_store(name.encode())

    # -- data plane -------------------------------------------------------

    def _slice(self, offset: int, size: int) -> memoryview:
        start = self._data_off + offset
        return self._view[start : start + size]

    def set_current_job(self, job_id_binary: bytes, label: str = "") -> None:
        """Stamp every subsequent create/put from this process with the
        job as creator (per-job byte accounting + quota enforcement).
        Called once after attach by workers/drivers with their JobID."""
        key = job_key(job_id_binary)
        self._job_key = key
        if key:
            self._job_labels[key] = label or job_id_binary.hex()[:8]

    def create_buffer(self, object_id: ObjectID, size: int) -> memoryview:
        if self._lib is None or self._h < 0:
            raise ObjectStoreError("store is closed")
        off = self._lib.ss_create_job(
            self._h, object_id.binary(), size, self._job_key)
        if off == SS_EXISTS:
            raise ObjectStoreError(f"object already exists: {object_id}")
        if off in (SS_NO_MEMORY, SS_TABLE_FULL):
            raise ObjectStoreFullError(
                f"object store out of {'memory' if off == SS_NO_MEMORY else 'table slots'}"
            )
        if off == SS_QUOTA:
            raise QuotaExceededError(
                f"job {self._job_labels.get(self._job_key, self._job_key)} "
                f"is at its object-store byte quota")
        if off < 0:
            raise ObjectStoreError(f"create failed: {off}")
        return self._slice(off, size)

    def seal(self, object_id: ObjectID):
        if self._lib is None or self._h < 0:
            raise ObjectStoreError("store is closed")
        rc = self._lib.ss_seal(self._h, object_id.binary())
        if rc not in (SS_OK, SS_EXISTS):
            raise ObjectStoreError(f"seal failed: {rc}")

    def put_value(self, object_id: ObjectID, value) -> int:
        """One-copy put: create the writer-private shm buffer first, then
        serialize the frame directly into it, then seal (reference:
        plasma create→write→seal). The payload is copied exactly once —
        from the caller's arrays into shared memory; the pickle stream
        is written from a view of the pickler's buffer, never
        materialized as intermediate bytes. Returns stored size; the
        creator reference is dropped (the object is immediately
        evictable once unreferenced)."""
        sv = serialization.serialize_value(value)
        buf = self.create_buffer(object_id, sv.size)
        try:
            sv.write_into(buf)
        except BaseException:
            self.delete(object_id)  # abort the unsealed create
            raise
        self.seal(object_id)
        self.release(object_id)
        return sv.size

    def put_serialized(self, object_id: ObjectID, pickled: bytes, buffers) -> int:
        """Write a framed serialized value; returns stored size."""
        size = serialization.serialized_size(pickled, buffers)
        buf = self.create_buffer(object_id, size)
        serialization.write_to(buf, pickled, buffers)
        self.seal(object_id)
        self.release(object_id)
        return size

    def put_raw(self, object_id: ObjectID, data: bytes | memoryview) -> int:
        """Store pre-framed bytes verbatim (used by object transfer)."""
        data = memoryview(data)
        buf = self.create_buffer(object_id, data.nbytes)
        serialization._fast_copy(buf, data)
        self.seal(object_id)
        self.release(object_id)
        return data.nbytes

    def get_buffer(self, object_id: ObjectID, timeout: float | None = -1
                   ) -> memoryview | None:
        """Framed bytes of a sealed object as a zero-copy view, or None.

        The returned memoryview holds one store reference (via PlasmaBuffer):
        the object cannot be evicted until the view — and every view derived
        from it, including numpy arrays from `get` — is garbage-collected.

        timeout: -1/None = non-blocking; 0 = wait forever; >0 = wait seconds.
        """
        if self._lib is None or self._h < 0:
            raise ObjectStoreError("store is closed")
        size = ctypes.c_uint64()
        t = -1.0 if timeout is None else float(timeout)
        off = self._lib.ss_get(self._h, object_id.binary(), ctypes.byref(size), t)
        if off in (SS_NOT_FOUND, SS_NOT_SEALED):
            return None
        if off == SS_TIMEOUT:
            raise ObjectTimeoutError(f"timed out waiting for {object_id}")
        if off < 0:
            raise ObjectStoreError(f"get failed: {off}")
        raw = self._slice(off, size.value)
        return PlasmaBuffer(self, object_id.binary(), raw).export()

    def get(self, object_id: ObjectID, timeout: float | None = -1):
        buf = self.get_buffer(object_id, timeout)
        if buf is None:
            return None
        return serialization.deserialize(buf)

    def contains(self, object_id: ObjectID) -> bool:
        if self._lib is None or self._h < 0:
            return False
        return self._lib.ss_contains(self._h, object_id.binary()) == 2

    def release(self, object_id: ObjectID):
        if self._lib is None or self._h < 0:
            return  # closed: nothing to release (benign at shutdown)
        self._lib.ss_release(self._h, object_id.binary())

    def delete(self, object_id: ObjectID):
        if self._lib is None or self._h < 0:
            return
        self._lib.ss_delete(self._h, object_id.binary())

    def evict(self, nbytes: int) -> int:
        if self._lib is None or self._h < 0:
            return 0
        return self._lib.ss_evict(self._h, nbytes)

    # -- ownership GC / recovery plane ------------------------------------

    def set_primary(self, object_id: ObjectID, flag: bool = True) -> bool:
        """Mark (or clear) the primary-copy location hint. The raylet
        sets it when it pins an object as the authoritative copy for an
        owner; replicas pulled from peers stay unmarked. Advisory: loss
        sweeps and the drop_objects chaos fault use it to tell primary
        data from caches. Returns False when the object is absent."""
        if self._lib is None or self._h < 0:
            return False
        return self._lib.ss_set_primary(
            self._h, object_id.binary(), 1 if flag else 0) == SS_OK

    def is_primary(self, object_id: ObjectID) -> bool:
        if self._lib is None or self._h < 0:
            return False
        return self._lib.ss_is_primary(self._h, object_id.binary()) == 1

    def refcount(self, object_id: ObjectID) -> int:
        """Client reference count of a stored object (creator + live
        buffer views), or -1 when absent. The owner's free-on-zero path
        checks this before force-delete: yanking a slot with mapped
        views alive would corrupt zero-copy readers."""
        if self._lib is None or self._h < 0:
            return -1
        rc = self._lib.ss_refcount(self._h, object_id.binary())
        return -1 if rc < 0 else int(rc)

    def list_sealed(self, max_objects: int = 65536) -> list:
        """Sealed objects as (ObjectID, primary, referenced) rows — a
        per-shard-consistent snapshot for chaos sweeps and loss
        accounting."""
        if self._lib is None or self._h < 0:
            return []
        ids = (ctypes.c_uint8 * (max_objects * 16))()
        flags = (ctypes.c_uint8 * max_objects)()
        n = self._lib.ss_list_sealed(self._h, ids, flags, max_objects)
        out = []
        for i in range(max(n, 0)):
            oid = ObjectID(bytes(ids[i * 16:(i + 1) * 16]))
            out.append((oid, bool(flags[i] & 1), bool(flags[i] & 2)))
        return out

    # -- per-job accounting (multi-tenant quota plane) --------------------

    def set_job_quota(self, job_id_binary: bytes, quota_bytes: int,
                      label: str = "") -> None:
        """Set (0 = clear) a job's object-store byte quota on this
        arena. A job at its quota reclaims its own evictable objects
        first, then gets QuotaExceededError — never another job's
        bytes."""
        if self._lib is None or self._h < 0:
            raise ObjectStoreError("store is closed")
        key = job_key(job_id_binary)
        if not key:
            return  # nil job: untracked by design
        self._job_labels[key] = label or job_id_binary.hex()[:8]
        rc = self._lib.ss_set_job_quota(self._h, key, quota_bytes)
        if rc == SS_TABLE_FULL:
            raise ObjectStoreError("job accounting table full")
        if rc != SS_OK:
            raise ObjectStoreError(f"set_job_quota failed: {rc}")

    def job_stats(self, job_id_binary: bytes) -> dict | None:
        """This job's accounting row, or None if it never touched the
        store (and has no quota)."""
        if self._lib is None or self._h < 0:
            return None
        key = job_key(job_id_binary)
        return self._job_stats_by_key(key)

    def _job_stats_by_key(self, key: int) -> dict | None:
        if not key:
            return None
        row = (ctypes.c_uint64 * 5)()
        if self._lib.ss_job_stats(self._h, key, row) != SS_OK:
            return None
        return {
            "quota": row[0],
            "used": row[1],
            "evicted_bytes": row[2],
            "quota_rejects": row[3],
            "num_objects": row[4],
        }

    def jobs(self) -> dict:
        """All active accounting rows keyed by job label (hex prefix of
        the JobID when known, else the raw key)."""
        out = {}
        if self._lib is None or self._h < 0:
            return out
        keys = (ctypes.c_uint64 * 32)()
        n = self._lib.ss_job_list(self._h, keys, 32)
        for i in range(max(n, 0)):
            st = self._job_stats_by_key(keys[i])
            if st is not None:
                label = self._job_labels.get(keys[i], f"{keys[i]:016x}")
                out[label] = st
        return out

    def evict_job(self, nbytes: int, job_id_binary: bytes) -> int:
        """Reclaim up to nbytes from ONE job's own evictable objects."""
        if self._lib is None or self._h < 0:
            return 0
        key = job_key(job_id_binary)
        if not key:
            return 0
        return self._lib.ss_evict_job(self._h, nbytes, key)

    @property
    def num_shards(self) -> int:
        if self._lib is None or self._h < 0:
            return 0
        return self._lib.ss_num_shards(self._h)

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        alloc = ctypes.c_uint64()
        n = ctypes.c_uint32()
        ref = ctypes.c_uint64()
        wait = ctypes.c_uint64()
        cont = ctypes.c_uint64()
        evd = ctypes.c_uint64()
        if self._lib is None or self._h < 0:
            lib = None
        else:
            lib = self._lib
            lib.ss_stats2(
                self._h, ctypes.byref(cap), ctypes.byref(alloc),
                ctypes.byref(n), ctypes.byref(ref), ctypes.byref(wait),
                ctypes.byref(cont), ctypes.byref(evd)
            )
        return {
            "capacity": cap.value,
            "allocated": alloc.value,
            "num_objects": n.value,
            # bytes a create CANNOT reclaim (unsealed or still
            # referenced); `allocated` additionally counts evictable
            # garbage — use `referenced` for backpressure
            "referenced": ref.value,
            # contention instrumentation, summed over index shards and
            # allocator regions (per-shard breakdown: shard_stats())
            "lock_wait_ns": wait.value,
            "lock_contended": cont.value,
            "evicted_objects": evd.value,
        }

    def metrics_text(self) -> str:
        """Prometheus exposition of store + per-shard contention stats,
        computed at scrape time (daemon `/metrics` extra_text — the
        flight-recorder view of the sharded shm plane)."""
        st = self.stats()
        lines = [
            "# TYPE object_store_lock_wait_ns_total counter",
            f"object_store_lock_wait_ns_total {st['lock_wait_ns']}",
            "# TYPE object_store_lock_contended_total counter",
            f"object_store_lock_contended_total {st['lock_contended']}",
            "# TYPE object_store_evicted_objects_total counter",
            f"object_store_evicted_objects_total {st['evicted_objects']}",
            "# TYPE object_store_referenced_bytes gauge",
            f"object_store_referenced_bytes {st['referenced']}",
            "# TYPE object_store_shards gauge",
            f"object_store_shards {self.num_shards}",
        ]
        job_rows = self.jobs()
        if job_rows:
            lines.append("# TYPE object_store_job_used_bytes gauge")
            for label, jst in sorted(job_rows.items()):
                lines.append(
                    f'object_store_job_used_bytes{{job="{label}"}} '
                    f"{jst['used']}")
                lines.append(
                    f'object_store_job_quota_bytes{{job="{label}"}} '
                    f"{jst['quota']}")
                lines.append(
                    f'object_store_job_evicted_bytes{{job="{label}"}} '
                    f"{jst['evicted_bytes']}")
                lines.append(
                    f'object_store_job_quota_rejects{{job="{label}"}} '
                    f"{jst['quota_rejects']}")
        shard_rows = self.shard_stats()
        if shard_rows:
            lines.append("# TYPE object_store_shard_lock_wait_ns gauge")
            for i, row in enumerate(shard_rows):
                lines.append(
                    f'object_store_shard_lock_wait_ns{{shard="{i}"}} '
                    f"{row['lock_wait_ns']}")
                lines.append(
                    f'object_store_shard_contended{{shard="{i}"}} '
                    f"{row['lock_contended']}")
                lines.append(
                    f'object_store_shard_evicted{{shard="{i}"}} '
                    f"{row['evicted_objects']}")
                lines.append(
                    f'object_store_shard_objects{{shard="{i}"}} '
                    f"{row['num_objects']}")
        return "\n".join(lines) + "\n"

    def shard_stats(self) -> list:
        """Per-shard contention/eviction rows (index stripe + its
        allocator region), for bench auditing and hot-shard triage."""
        out = []
        if self._lib is None or self._h < 0:
            return out
        row = (ctypes.c_uint64 * 8)()
        for shard in range(self._lib.ss_num_shards(self._h)):
            if self._lib.ss_shard_stats(self._h, shard, row) != SS_OK:
                break
            out.append({
                "lock_wait_ns": row[0],
                "lock_contended": row[1],
                "lock_acquisitions": row[2],
                "evicted_objects": row[3],
                "evicted_bytes": row[4],
                "num_objects": row[5],
                "region_allocated": row[6],
                "region_lock_wait_ns": row[7],
            })
        return out
