"""Node bootstrap: spawn the daemons that make up a ray_tpu node.

Reference: `python/ray/_private/node.py` — `start_head_processes` (GCS then
raylet, dashboard, monitors) and `python/ray/_private/services.py` command
assembly. Also provides `Cluster`, the multi-node-on-one-machine testing
mechanism (reference: `python/ray/cluster_utils.py:135` — one raylet + store
per simulated node, one shared GCS).
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class ProcessHandle:
    def __init__(self, proc: subprocess.Popen, ready_line: str, log_path: str):
        self.proc = proc
        self.ready_line = ready_line
        self.log_path = log_path

    def terminate(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()


def _spawn(args: List[str], log_path: str, ready_prefix: str,
           timeout: float = 240.0, env: dict | None = None,
           detach: bool = False) -> ProcessHandle:
    """Spawn a daemon and wait for its READY line. `detach` puts it in
    its own session (CLI-started nodes that outlive the launcher). The
    ready wait is non-blocking so a wedged daemon that never prints and
    never exits still trips the deadline — generous by default because
    on a loaded box interpreter start alone can take tens of seconds."""
    env = dict(env or os.environ)
    env.setdefault("PYTHONPATH", REPO_ROOT)
    # Daemons never touch accelerators; workers get chips explicitly. Keep
    # the original platform setting so raylets can hand it to TPU workers.
    if "JAX_PLATFORMS" in env and "RAY_TPU_WORKER_JAX_PLATFORMS" not in env:
        env["RAY_TPU_WORKER_JAX_PLATFORMS"] = env["JAX_PLATFORMS"]
    env["JAX_PLATFORMS"] = "cpu"
    logfile = open(log_path, "wb" if not detach else "ab")
    proc = subprocess.Popen(
        args, stdout=subprocess.PIPE, stderr=logfile, env=env,
        cwd=REPO_ROOT, start_new_session=detach,
    )
    logfile.close()
    os.set_blocking(proc.stdout.fileno(), False)
    deadline = time.monotonic() + timeout
    buf = b""
    while time.monotonic() < deadline:
        chunk = proc.stdout.read()
        if chunk:
            buf += chunk
            for line in buf.decode(errors="replace").splitlines():
                if line.startswith(ready_prefix):
                    return ProcessHandle(proc, line.strip(), log_path)
        if proc.poll() is not None:
            raise RuntimeError(
                f"daemon exited: {args!r}; log: {log_path}: "
                + open(log_path, errors="replace").read()[-2000:]
            )
        time.sleep(0.02)
    proc.terminate()
    raise RuntimeError(f"daemon not ready in {timeout}s: {args!r}")


class NodeHandle:
    def __init__(self, raylet: ProcessHandle):
        parts = raylet.ready_line.split()
        self.raylet_addr = parts[1]
        self.store_name = parts[2]
        self.node_id_hex = parts[3]
        self.process = raylet


class Cluster:
    """A real multi-daemon cluster on one machine.

    `Cluster(num_nodes=3)` starts one GCS and three raylets, each with its
    own shared-memory arena and worker pool — the mechanism every
    multi-node test in the reference uses (`ray_start_cluster`).
    """

    def __init__(
        self,
        head_resources: Dict[str, float] | None = None,
        object_store_memory: int | None = None,
        session_dir: str | None = None,
        gcs_persistence: bool = False,
        gcs_store: bool = False,
    ):
        ts = int(time.time() * 1000)
        self.session_dir = session_dir or f"/tmp/ray_tpu/session_{ts}_{os.getpid()}"
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.object_store_memory = object_store_memory
        self.gcs_persistence = gcs_persistence
        # write-through external store (Redis-role FileStoreClient):
        # durability per mutation, no snapshot-interval freshness window
        self.gcs_store = gcs_store
        self.gcs: Optional[ProcessHandle] = None
        self.nodes: List[NodeHandle] = []
        self._start_gcs()
        if head_resources is not None:
            self.add_node(head_resources)

    def _log(self, name: str) -> str:
        return os.path.join(self.session_dir, "logs", name)

    def _start_gcs(self, port: int = 0):
        args = [sys.executable, "-m", "ray_tpu._private.gcs",
                "--port", str(port),
                "--log-file", self._log("gcs.log")]
        if self.gcs_persistence:
            args += ["--persist-path",
                     os.path.join(self.session_dir, "gcs_state.pkl")]
        if self.gcs_store:
            args += ["--store-path",
                     os.path.join(self.session_dir, "gcs_store")]
        self.gcs = _spawn(args, self._log("gcs.out"), "GCS_READY")
        self.gcs_addr = self.gcs.ready_line.split()[1]

    def restart_gcs(self):
        """Kill and respawn the GCS on the same address (fault-tolerance
        testing; requires gcs_persistence or gcs_store so tables
        survive — reference: test_gcs_fault_tolerance.py's
        restart_gcs_server)."""
        if not (self.gcs_persistence or self.gcs_store):
            raise RuntimeError(
                "restart_gcs requires gcs_persistence or gcs_store")
        port = int(self.gcs_addr.rsplit(":", 1)[1])
        self.gcs.terminate()
        self._start_gcs(port=port)

    def add_node(self, resources: Dict[str, float],
                 object_store_memory: int | None = None,
                 labels: Dict[str, str] | None = None) -> NodeHandle:
        args = [
            sys.executable, "-m", "ray_tpu._private.raylet",
            "--gcs-addr", self.gcs_addr,
            "--resources", json.dumps(resources),
            "--session-dir", self.session_dir,
            "--log-file", self._log(f"raylet-{len(self.nodes)}.log"),
        ]
        # always explicit ({} for plain nodes): a test-cluster node must
        # never inherit slice identity from the host's TPU-VM env vars
        args += ["--labels", json.dumps(labels or {})]
        mem = object_store_memory or self.object_store_memory
        if mem:
            args += ["--object-store-memory", str(mem)]
        raylet = _spawn(args, self._log(f"raylet-{len(self.nodes)}.out"),
                        "RAYLET_READY")
        node = NodeHandle(raylet)
        self.nodes.append(node)
        return node

    def add_slice(self, slice_type: str, num_hosts: int,
                  chips_per_host: int = 4, cpus_per_host: float = 4.0,
                  name: str | None = None,
                  extra_labels: Dict[str, str] | None = None
                  ) -> List[NodeHandle]:
        """Simulate one TPU pod slice: `num_hosts` raylets sharing a slice
        name, each owning its host-local chips (the reference's TPU-VM
        topology, accelerators/tpu.py:341-369, as local processes — the
        multi-host analogue of `ray_start_cluster`)."""
        from ray_tpu._private import accelerators as acc

        name = name or f"{slice_type}-{len(self.nodes)}"
        handles = []
        for host_id in range(num_hosts):
            labels = {
                acc.LABEL_SLICE_NAME: name,
                acc.LABEL_SLICE_TYPE: slice_type,
                acc.LABEL_SLICE_HOST_ID: str(host_id),
                acc.LABEL_SLICE_NUM_HOSTS: str(num_hosts),
                **(extra_labels or {}),
            }
            handles.append(self.add_node(
                {"CPU": cpus_per_host, "TPU": float(chips_per_host)},
                labels=labels))
        return handles

    @property
    def head_node(self) -> NodeHandle:
        return self.nodes[0]

    def remove_node(self, node: NodeHandle):
        node.process.terminate()
        self.nodes.remove(node)
        # terminate() SIGKILLs after a 5s grace — the raylet may never
        # reach its own store.destroy(), so reap the arena here too
        try:
            os.unlink(f"/dev/shm{node.store_name}")
        except OSError:
            pass

    def shutdown(self):
        # Arena cleanup is scoped to THIS session's stores — other clusters
        # on the machine own their own /dev/shm entries.
        store_names = [n.store_name for n in self.nodes]
        for node in self.nodes:
            node.process.terminate()
        if self.gcs:
            self.gcs.terminate()
        self.nodes.clear()
        for name in store_names:
            try:
                os.unlink(f"/dev/shm{name}")
            except OSError:
                pass
