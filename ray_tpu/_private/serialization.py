"""Object serialization: cloudpickle + pickle-5 out-of-band zero-copy buffers.

Mirrors the reference's split (`python/ray/_private/serialization.py`):
values are cloudpickled with protocol 5 and large contiguous buffers (numpy
arrays, arrow buffers, bytes) are captured out-of-band so that storing to the
shared-memory object store and reading back is zero-copy — on `get`, buffers
are reconstructed as memoryviews over the store's mmap, and numpy arrays are
views onto shared memory.

Wire layout of a stored object (64-byte aligned buffers):

    u32 magic | u32 n_buffers | u64 size[n] ... | pad | buf0 | pad | buf1 ...

buf0 is always the pickle stream; buf1.. are the out-of-band buffers.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, List, Sequence

import cloudpickle

from ray_tpu._private.object_ref import ObjectRef

MAGIC = 0x52415931  # "RAY1"
_ALIGN = 64

# Registry of custom serializers, mirroring ray.util.register_serializer.
_custom_serializers: dict[type, tuple[Callable, Callable]] = {}


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable):
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type):
    _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffers: List):
        super().__init__(
            file, protocol=5, buffer_callback=lambda b: buffers.append(b.raw())
        )
        # ObjectRefs pickled anywhere inside the value (nested in
        # containers included). The submit path uses them two ways: a
        # non-empty list keeps the spec out of multi-task actor batches
        # (resolving such a ref may need an earlier in-batch task's
        # withheld reply — deadlock), and the owner pins each one for
        # the task's lifetime so dropping the caller's handle cannot
        # free an object the task still needs.
        self.object_refs: List[ObjectRef] = []
        self.saw_object_ref = False

    def reducer_override(self, obj):
        if type(obj) is ObjectRef:
            self.saw_object_ref = True
            self.object_refs.append(obj)
        ser = _custom_serializers.get(type(obj))
        if ser is not None:
            serializer, deserializer = ser
            return (_reconstruct_custom, (type(obj), deserializer, serializer(obj)))
        return super().reducer_override(obj)


def _reconstruct_custom(cls, deserializer, payload):
    return deserializer(payload)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def serialize(value: Any) -> tuple[bytes, List[memoryview]]:
    """Serialize to (pickle_bytes, out_of_band_buffers)."""
    buffers: List[memoryview] = []
    f = io.BytesIO()
    _Pickler(f, buffers).dump(value)
    return f.getvalue(), buffers


class SerializedValue:
    """A pickled value held without any copy of its payload.

    `pickled` is a zero-copy view over the pickler's internal buffer
    (io.BytesIO.getbuffer() — the view keeps the BytesIO alive) and
    `buffers` are pickle-5 out-of-band views over the original arrays,
    so after `serialize_value` NOTHING large has been copied yet. The
    one-copy put protocol (reference: plasma client create→write→seal,
    `src/ray/object_manager/plasma/client.cc`) is then:

        sv = serialize_value(value)
        buf = store.create_buffer(oid, sv.size)   # writer-private shm
        sv.write_into(buf)                        # the ONE payload copy
        store.seal(oid)

    `to_bytes()` materializes the framed object for the in-band path.
    """

    __slots__ = ("pickled", "buffers", "size")

    def __init__(self, pickled: memoryview, buffers: List[memoryview]):
        self.pickled = pickled
        # normalize to flat byte views once, so sizing and writing agree
        self.buffers = [
            b if b.ndim == 1 and b.format == "B" else b.cast("B")
            for b in buffers
        ]
        self.size = serialized_size(pickled, self.buffers)

    def write_into(self, dst: memoryview) -> int:
        """Write the framed object in place; returns bytes written."""
        return write_to(dst, self.pickled, self.buffers)

    def to_bytes(self) -> bytes:
        out = bytearray(self.size)
        write_to(memoryview(out), self.pickled, self.buffers)
        return bytes(out)


def serialize_value(value: Any) -> SerializedValue:
    """Pickle `value` capturing out-of-band buffers, copying nothing
    large: the pickle stream stays a view of the pickler's buffer and
    the oob buffers stay views of the caller's arrays."""
    return serialize_value_with_refs(value)[0]


def serialize_value_with_refs(
        value: Any) -> tuple[SerializedValue, List[ObjectRef]]:
    """`serialize_value` plus every ObjectRef pickled anywhere inside
    `value` — the executor's return path must know them to hand the
    borrows off to the caller before its own handles die."""
    buffers: List[memoryview] = []
    f = io.BytesIO()
    p = _Pickler(f, buffers)
    p.dump(value)
    return SerializedValue(f.getbuffer(), buffers), p.object_refs


def serialize_into(dst: memoryview, value: Any) -> int:
    """Serialize `value` writing the frame directly into `dst` (a
    pre-created shm view). Returns bytes written; raises ValueError when
    the frame does not fit. Callers that need exact sizing should use
    `serialize_value` + `create_buffer(sv.size)` + `sv.write_into`."""
    sv = serialize_value(value)
    if sv.size > dst.nbytes:
        raise ValueError(
            f"serialized frame ({sv.size} B) exceeds destination "
            f"({dst.nbytes} B)")
    return sv.write_into(dst)


def dumps_with_ref_flag(value: Any) -> tuple[bytes, list]:
    """Like `dumps`, additionally returning every ObjectRef pickled
    anywhere inside `value` (nested in containers included) — truthy
    exactly when the old boolean flag was."""
    buffers: List[memoryview] = []
    f = io.BytesIO()
    p = _Pickler(f, buffers)
    p.dump(value)
    return pack(f.getvalue(), buffers), p.object_refs


def serialized_size(pickled: bytes, buffers: Sequence[memoryview]) -> int:
    n = 1 + len(buffers)
    header = 8 + 8 * n
    total = _align(header)
    total += _align(len(pickled))
    for b in buffers:
        total += _align(b.nbytes)
    return total


_native_copy_lib = None
_MT_COPY_MIN = 8 << 20  # below this a plain memcpy wins (thread spawn cost)


def _fast_copy(dst: memoryview, src: memoryview) -> None:
    """Copy a large contiguous buffer with the native multi-threaded
    memcopy (reference: the plasma client's memcopy_threads,
    `src/ray/object_manager/plasma/client.cc`) — one memcpy thread
    cannot saturate multi-channel DRAM, and big puts are exactly the
    copy-bound path. Small copies and missing-lib fall back to the
    plain buffer assignment."""
    global _native_copy_lib
    if dst.nbytes != src.nbytes:
        # the raw-pointer native path has no bounds — keep the loud
        # ValueError the plain buffer assignment used to raise
        raise ValueError(
            f"copy size mismatch: dst {dst.nbytes} != src {src.nbytes}")
    if src.nbytes < _MT_COPY_MIN:
        dst[:] = src
        return
    if _native_copy_lib is None:
        try:
            from ray_tpu.native import load_shm_store

            _native_copy_lib = load_shm_store()
        except Exception:  # noqa: BLE001 — fallback is correct, just slower
            _native_copy_lib = False
    if _native_copy_lib is False:
        dst[:] = src
        return
    import os as os_mod

    import numpy as np

    threads = int(os_mod.environ.get("RAY_TPU_MEMCPY_THREADS", "0"))
    d = np.frombuffer(dst, np.uint8)
    s = np.frombuffer(src, np.uint8)
    _native_copy_lib.ss_memcpy_mt(d.ctypes.data, s.ctypes.data,
                                  src.nbytes, threads)


def write_to(dst: memoryview, pickled: bytes, buffers: Sequence[memoryview]) -> int:
    """Write the framed object into a writable buffer; returns bytes written."""
    n = 1 + len(buffers)
    struct.pack_into("<II", dst, 0, MAGIC, n)
    sizes = [len(pickled)] + [b.nbytes for b in buffers]
    struct.pack_into(f"<{n}Q", dst, 8, *sizes)
    off = _align(8 + 8 * n)
    dst[off : off + len(pickled)] = pickled
    off += _align(len(pickled))
    for b in buffers:
        flat = b.cast("B") if b.ndim != 1 or b.format != "B" else b
        _fast_copy(dst[off : off + flat.nbytes], flat)
        off += _align(flat.nbytes)
    return off


def pack(pickled: bytes, buffers: Sequence[memoryview]) -> bytes:
    out = bytearray(serialized_size(pickled, buffers))
    write_to(memoryview(out), pickled, buffers)
    return bytes(out)


def deserialize(src: memoryview) -> Any:
    """Reconstruct a value from a framed buffer (zero-copy for oob buffers)."""
    magic, n = struct.unpack_from("<II", src, 0)
    if magic != MAGIC:
        raise ValueError("corrupt object: bad magic")
    sizes = struct.unpack_from(f"<{n}Q", src, 8)
    off = _align(8 + 8 * n)
    views: List[memoryview] = []
    for size in sizes:
        views.append(src[off : off + size])
        off += _align(size)
    return pickle.loads(views[0], buffers=views[1:])


def dumps(value: Any) -> bytes:
    """One-shot serialize to a self-contained frame (for RPC inlining)."""
    return pack(*serialize(value))


def loads(data: bytes | memoryview) -> Any:
    return deserialize(memoryview(data))
