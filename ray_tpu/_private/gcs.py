"""GCS — Global Control Service: the head-node metadata/control plane.

Reference: `src/ray/gcs/gcs_server/` — cluster metadata authority and
cluster-level scheduler: node membership + health checks
(`GcsNodeManager`, `GcsHealthCheckManager`), actor directory with
fault-tolerant restart (`GcsActorManager` + `GcsActorScheduler`),
placement-group creation (`GcsPlacementGroupManager`), job table
(`GcsJobManager`), internal KV (`GcsKvManager`), pubsub
(`pubsub_handler`), and the resource-view sync loop (ray_syncer).

All tables are in-memory (the reference's default `InMemoryStoreClient`);
Redis-backed persistence for GCS fault tolerance is a later round.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import health as health_mod
from ray_tpu._private import rpc
from ray_tpu._private import sharded_table
from ray_tpu._private import task as task_mod
from ray_tpu._private.config import Config
from ray_tpu._private.sharded_table import ShardedTable
from ray_tpu.util import events as export_events
from ray_tpu._private.rpc import ClientPool, ConnectionLost, RpcError, RpcServer
from ray_tpu._private.scheduling import (
    ClusterView,
    pick_node,
    place_bundles,
    place_slice_bundles,
)

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: rpc::ActorTableData::ActorState).
PENDING = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 config: Config | None = None,
                 persist_path: str | None = None,
                 store_path: str | None = None):
        self.config = config or Config.from_env()
        self.server = RpcServer(host, port)
        self.clients = ClientPool()
        self.view = ClusterView()

        # Tables. The hot-write tables (actor directory, bounded task-event
        # log) are keyed-shard maps: concurrent registrations and event
        # ingestion spread over shards with per-shard counters in /metrics,
        # and write-through persistence routes by the same shard index onto
        # per-shard store threads (see _persist).
        self.kv: Dict[str, Dict[bytes, bytes]] = {}
        self.nodes: Dict[bytes, dict] = {}
        self.jobs: Dict[bytes, dict] = {}
        self.actors: ShardedTable = ShardedTable(name="actors")
        self.named_actors: Dict[str, bytes] = {}
        self.placement_groups: Dict[bytes, dict] = {}
        self.task_events: ShardedTable = ShardedTable(name="task_events")
        self.subscribers: Dict[str, List[str]] = {}
        self._last_heartbeat: Dict[bytes, float] = {}
        self._pending_actors: List[bytes] = []
        self._scheduling_actors: set = set()
        self._pending_pgs: List[bytes] = []
        self._bg_tasks: list = []
        self._retry_wakeup = asyncio.Event()
        # orders availability deltas against death publishes on the
        # "resources" gossip channel (see _publish_resource_delta)
        self._resources_pub_lock = asyncio.Lock()
        # Persistence (reference: RedisStoreClient-backed GCS tables,
        # store_client/redis_store_client.h — here a snapshot file):
        # tables survive a GCS restart; raylets reregister via the
        # heartbeat reregister handshake, clients reconnect through
        # their ReconnectingClient handles.
        self.persist_path = persist_path
        # Pluggable write-through StoreClient (reference:
        # RedisStoreClient, src/ray/gcs/store_client/
        # redis_store_client.h): every table MUTATION is durable at
        # write time — a GCS killed between snapshot intervals restarts
        # with current tables, not the last snapshot's.
        from ray_tpu._private.store_client import make_store_client

        self.store = make_store_client(store_path)
        # One single-thread writer per table shard: same key → same shard
        # → same thread keeps per-key mutation order, while writes for
        # different shards no longer serialize on one store thread.
        self._store_pools = ([
            ThreadPoolExecutor(1, f"gcs-store-{i}")
            for i in range(ShardedTable.DEFAULT_SHARDS)]
            if self.store else None)
        # deadman probe over the persist executors: beats land in
        # _store_put on the shard threads; backlog is the queued writes
        # across all shards, so a wedged store thread (disk hang) reads
        # as frozen-counter-with-backlog and gets its stack captured
        self._store_probe = (health_mod.watch_loop(
            "gcs_store", backlog_fn=self._store_backlog)
            if self._store_pools else None)
        self._watchdog: Optional[health_mod.Watchdog] = None
        if self.store is not None and self.store.tables():
            self._load_from_store()
        elif persist_path:
            self._load_snapshot()
            if self.store is not None:
                # migration: snapshot-restored tables must reach the
                # store NOW — the next restart takes the (then
                # non-empty) store as authoritative, and anything left
                # only in the snapshot would silently vanish
                self._dump_all_to_store()

    _SNAPSHOT_TABLES = ("kv", "jobs", "actors", "named_actors",
                        "placement_groups", "subscribers", "task_events")

    def _load_snapshot(self):
        import pickle

        try:
            with open(self.persist_path, "rb") as f:
                data = pickle.load(f)
        except FileNotFoundError:
            return
        except Exception:  # noqa: BLE001 — torn write: start fresh
            logger.exception("snapshot unreadable; starting fresh")
            return
        for name in self._SNAPSHOT_TABLES:
            if name in data:
                setattr(self, name, data[name])
        self._reshard_tables()
        self._resume_pending("snapshot")

    def _load_from_store(self):
        """Rebuild tables from the write-through StoreClient — the
        authoritative copy (fresher than any snapshot: it has every
        mutation up to the instant of death)."""
        self.actors = self.store.get_all("actors")
        self.placement_groups = self.store.get_all("placement_groups")
        self.jobs = self.store.get_all("jobs")
        self.named_actors = {
            k.decode(): v
            for k, v in self.store.get_all("named_actors").items()}
        self.kv = {}
        for table in self.store.tables():
            if table.startswith("kv:"):
                self.kv[table[3:]] = self.store.get_all(table)
        self._reshard_tables()
        self._resume_pending("store")

    def _reshard_tables(self):
        """Restored tables arrive as plain dicts (store dumps, pre-shard
        snapshots); rewrap the hot tables, keeping insertion order as the
        recency order. A ShardedTable from a current snapshot unpickles
        as itself and passes through."""
        for name in ("actors", "task_events"):
            table = getattr(self, name)
            if not isinstance(table, ShardedTable):
                setattr(self, name,
                        ShardedTable.from_mapping(table, name=name))

    def _resume_pending(self, source: str):
        # resume interrupted placements: anything not terminal goes back
        # on the pending queues
        for actor_id, info in self.actors.items():
            if info["state"] in (PENDING, RESTARTING):
                self._pending_actors.append(actor_id)
        for pg_id, pg in self.placement_groups.items():
            if pg["state"] == "PENDING":
                self._pending_pgs.append(pg_id)
        logger.info(
            "restored GCS state from %s: %d actors, %d PGs, %d jobs, "
            "%d kv ns", source, len(self.actors),
            len(self.placement_groups), len(self.jobs), len(self.kv))

    async def _publish_resource_delta(self, node_id: bytes, data: dict):
        """Resource-gossip deltas ride a per-channel LOCK shared with the
        death publish (reference ordering concern: ray_syncer versions
        its messages): a heartbeat handler suspended mid-publish cannot
        have its delta land AFTER a concurrent death publish and
        resurrect the node in peer views — the lock serializes the two,
        and aliveness is re-checked inside it."""
        async with self._resources_pub_lock:
            node = self.nodes.get(node_id)
            if node is None or not node["alive"]:
                return  # died while we waited: death publish stands
            await self.publish("resources", data)

    def _dump_all_to_store(self):
        for actor_id, rec in self.actors.items():
            self.store.put("actors", actor_id, rec)
        for pg_id, rec in self.placement_groups.items():
            self.store.put("placement_groups", pg_id, rec)
        for job_id, rec in self.jobs.items():
            self.store.put("jobs", job_id, rec)
        for name, actor_id in self.named_actors.items():
            self.store.put("named_actors", name.encode(), actor_id)
        for ns, table in self.kv.items():
            for k, v in table.items():
                self.store.put(f"kv:{ns}", k, v)

    # -- write-through persistence (StoreClient seam) -------------------

    def _persist(self, table: str, key: bytes, record) -> None:
        """Serialize on the loop thread (consistent view of the record),
        write on the key's shard-routed store thread (ordered per key —
        one writer thread per shard keeps mutation order, and writes to
        different shards no longer queue behind each other)."""
        if self.store is None:
            return
        import pickle

        blob = pickle.dumps(record)
        pool = self._store_pools[
            sharded_table.shard_index(key, len(self._store_pools))]
        pool.submit(self._store_put, table, key, blob)

    def _store_backlog(self) -> int:
        return sum(p._work_queue.qsize()
                   for p in (self._store_pools or []))

    def _store_put(self, table, key, blob):
        if self._store_probe is not None:
            self._store_probe.beat()
        try:
            self.store.put_blob(table, key, blob)
        except Exception:  # noqa: BLE001 — durability is best-effort
            logger.exception("store write failed: %s/%s", table, key.hex())

    def _unpersist(self, table: str, key: bytes) -> None:
        if self.store is None:
            return
        pool = self._store_pools[
            sharded_table.shard_index(key, len(self._store_pools))]
        pool.submit(self.store.delete, table, key)

    def _write_snapshot(self):
        self._write_snapshot_bytes(self._serialize_snapshot())

    def _serialize_snapshot(self) -> bytes:
        """MUST run on the event-loop thread: pickling live tables while
        handlers mutate them would see dicts change mid-iteration."""
        import pickle

        data = {name: getattr(self, name)
                for name in self._SNAPSHOT_TABLES}
        return pickle.dumps(data)

    def _write_snapshot_bytes(self, blob: bytes):
        tmp = self.persist_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self.persist_path)  # atomic swap

    async def _snapshot_loop(self):
        while True:
            await asyncio.sleep(1.0)
            try:
                blob = self._serialize_snapshot()  # on-loop: consistent
                await asyncio.get_event_loop().run_in_executor(
                    None, self._write_snapshot_bytes, blob)
            except Exception:  # noqa: BLE001
                logger.exception("snapshot write failed")

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _metrics_text(self) -> str:
        states: Dict[str, int] = {}
        for a in self.actors.values():
            states[a["state"]] = states.get(a["state"], 0) + 1
        from ray_tpu._private import scheduling as scheduling_mod

        lines = [
            "# TYPE gcs_nodes_alive gauge",
            f"gcs_nodes_alive "
            f"{sum(1 for n in self.nodes.values() if n['alive'])}",
            f"gcs_placement_groups_pending {len(self._pending_pgs)}",
            # scheduler queue depth at the GCS: actors waiting for a
            # feasible node + pending PGs (flight-recorder plane)
            "# TYPE scheduler_queue_depth gauge",
            f"scheduler_queue_depth "
            f"{len(self._pending_actors) + len(self._pending_pgs)}",
            f"gcs_actors_pending {len(self._pending_actors)}",
            f"gcs_task_events {len(self.task_events)}",
        ]
        for state, count in states.items():
            lines.append(f'gcs_actors{{state="{state}"}} {count}')
        return ("\n".join(lines) + "\n"
                + self.actors.metrics_text()
                + self.task_events.metrics_text()
                + scheduling_mod.metrics_text()
                + rpc.metrics_text()
                + health_mod.metrics_text())

    async def start(self, metrics_port: int | None = None):
        self.server.register_all(self)
        await self.server.start()
        self._watchdog = health_mod.Watchdog(source="GCS").start()
        self._bg_tasks = [
            asyncio.ensure_future(self._health_check_loop()),
            asyncio.ensure_future(self._retry_loop()),
            # event-loop liveness: every handler (and the persist fan-in)
            # rides this loop — a blocked loop freezes the ticker
            health_mod.loop_ticker(
                health_mod.watch_loop("gcs_loop")),
        ]
        if self.persist_path:
            self._bg_tasks.append(
                asyncio.ensure_future(self._snapshot_loop()))
            self._retry_wakeup.set()  # kick restored pending work
        if metrics_port is not None:
            from ray_tpu.util.metrics import serve_metrics

            self._metrics_server, port = await serve_metrics(
                port=metrics_port, extra_text=self._metrics_text)
            logger.info("metrics on :%d/metrics", port)
            self.metrics_port = port
        logger.info("GCS listening on %s", self.server.address)
        return self

    _metrics_server = None

    async def stop(self):
        for t in self._bg_tasks:
            t.cancel()
        if self._watchdog is not None:
            self._watchdog.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
        if self.persist_path:
            try:
                # final snapshot can be tens of MB — write it off-loop
                # so in-flight replies drain while it lands
                await asyncio.get_running_loop().run_in_executor(
                    None, self._write_snapshot)
            except Exception:  # noqa: BLE001
                logger.exception("final snapshot failed")
        await self.clients.close_all()
        await self.server.stop()

    @property
    def address(self) -> str:
        return self.server.address

    # ------------------------------------------------------------------
    # pubsub (reference: src/ray/pubsub — push-based here since every
    # participant runs an RpcServer)
    # ------------------------------------------------------------------

    async def rpc_subscribe(self, req):
        self.subscribers.setdefault(req["channel"], [])
        if req["addr"] not in self.subscribers[req["channel"]]:
            self.subscribers[req["channel"]].append(req["addr"])
        return {"ok": True}

    async def publish(self, channel: str, data: Any):
        """Fan out concurrently with a short per-subscriber budget: a
        dead subscriber (exited driver/worker) must cost ~2s once — not
        a serial 10s connect-retry that stalls whichever RPC handler
        happened to publish."""
        subs = list(self.subscribers.get(channel, []))
        if not subs:
            return

        async def send(addr: str):
            client = await self.clients.get(addr)
            await client.notify("pubsub",
                                {"channel": channel, "data": data})

        results = await asyncio.gather(
            *[asyncio.wait_for(send(a), timeout=2.0) for a in subs],
            return_exceptions=True)
        for addr, result in zip(subs, results):
            # TimeoutError must be checked FIRST: on py3.11+ it IS a
            # subclass of OSError, and a busy-but-live subscriber that
            # blows the 2s budget must keep its subscription — dropping
            # it would silently starve the driver of actor updates
            if isinstance(result, asyncio.TimeoutError):
                logger.debug("pubsub to %s timed out", addr)
            elif isinstance(result, (ConnectionLost, OSError, RpcError)):
                # connection-dead: unsubscribe (removal must be
                # idempotent — concurrent publishes may both see it)
                if addr in self.subscribers.get(channel, []):
                    self.subscribers[channel].remove(addr)
                self.clients.invalidate(addr)

    # ------------------------------------------------------------------
    # node membership + resource view (GcsNodeManager + ray_syncer)
    # ------------------------------------------------------------------

    async def rpc_register_node(self, req):
        node_id = req["node_id"]
        self.nodes[node_id] = {
            "node_id": node_id,
            "raylet_addr": req["raylet_addr"],
            "total": req["total"],
            "available": req["available"],
            "alive": True,
            "hostname": req.get("hostname", ""),
            "labels": req.get("labels", {}),
        }
        self.view.update_node(node_id, req["raylet_addr"], req["total"],
                              req["available"],
                              labels=req.get("labels", {}))
        self._last_heartbeat[node_id] = time.monotonic()
        await export_events.report_async(
            "GCS", "INFO", "NODE_ADDED",
            f"node {node_id.hex()[:8]} joined",
            node_id=node_id.hex(), raylet_addr=req["raylet_addr"])
        await self.publish("nodes", {"event": "added", "node": self.nodes[node_id]})
        # seed peer raylets' views immediately (see the resource-gossip
        # delta push in rpc_heartbeat)
        await self.publish("resources", {
            "node_id": node_id,
            "raylet_addr": req["raylet_addr"],
            "total": req["total"],
            "available": req["available"],
            "labels": req.get("labels", {}),
        })
        self._retry_wakeup.set()
        return {"ok": True}

    async def rpc_heartbeat(self, req):
        if _fi._PLAN is not None:
            # chaos: delayed handling stalls liveness bookkeeping (the
            # health-check loop may mark the node dead meanwhile); a
            # dropped heartbeat never touches state at all
            if await _fi._PLAN.gcs_heartbeat():
                return {"ok": True}
        node_id = req["node_id"]
        node = self.nodes.get(node_id)
        if node is None or not node["alive"]:
            return {"ok": False, "reregister": True}
        node["available"] = req["available"]
        node["pending_demands"] = req.get("pending_demands", [])
        # idle tracking for autoscaler scale-down: a node is idle while
        # its resources are fully free, nothing is queued, and no worker
        # is bound to an actor or a running lease (live CPU actors hold
        # no resources, so resource-freeness alone would mark their node
        # reclaimable; warm idle-pool workers are excluded raylet-side)
        busy = (bool(node["pending_demands"])
                or req.get("busy_workers", 0) > 0
                or any(node["available"].get(k, 0.0) < v
                       for k, v in node["total"].items()))
        if busy:
            node.pop("idle_since", None)
        else:
            node.setdefault("idle_since", time.monotonic())
        self.view.update_node(node_id, node["raylet_addr"], node["total"],
                              req["available"])
        self._last_heartbeat[node_id] = time.monotonic()
        # Push-based resource gossip (reference: ray_syncer's streaming
        # node-resource sync, src/ray/common/ray_syncer/ray_syncer.h:88
        # — replacing the polled view): when a node's availability
        # CHANGES, fan the delta out to subscribed raylets immediately,
        # so spillback decisions ride fresh state instead of waiting out
        # a heartbeat period. The heartbeat reply's full view remains
        # the liveness-coupled fallback.
        if node.get("_pub_avail") != req["available"]:
            node["_pub_avail"] = dict(req["available"])
            await self._publish_resource_delta(node_id, {
                "node_id": node_id,
                "raylet_addr": node["raylet_addr"],
                "total": node["total"],
                "available": req["available"],
                "labels": node.get("labels") or {},
            })
        if req.get("idle_freed"):
            self._retry_wakeup.set()
        # Reply with the cluster resource view so raylets can spill back
        # tasks to other nodes (the ray_syncer gossip, piggybacked).
        return {"ok": True, "view": self._view_wire()}

    def _view_wire(self):
        return [
            {
                "node_id": n.node_id,
                "raylet_addr": n.raylet_addr,
                "total": n.total,
                "available": n.available,
                "labels": n.labels,
            }
            for n in self.view.alive_nodes()
        ]

    async def rpc_get_nodes(self, req):
        return list(self.nodes.values())

    # ------------------------------------------------------------------
    # task events (reference: GcsTaskManager, gcs_task_manager.h — the
    # bounded task table backing `ray list tasks` / `ray summary`)
    # ------------------------------------------------------------------

    _TASK_EVENTS_CAP = 10_000

    async def rpc_add_task_events(self, req):
        # wire form: (task_id, name, type, state, ts) tuples — see
        # CoreWorker._emit_task_event
        for task_id, name, task_type, state, ts in req["events"]:
            rec = self.task_events.get(task_id)
            if rec is None:
                rec = self.task_events[task_id] = {
                    "task_id": task_id,
                    "name": name,
                    "type": task_type,
                    "state": "",
                    "events": [],
                }
                while len(self.task_events) > self._TASK_EVENTS_CAP:
                    self.task_events.popitem_oldest()
            rec["state"] = state
            rec["events"].append((state, ts))
        return None  # notify-only path

    async def rpc_list_task_events(self, req):
        limit = req.get("limit", 1000)
        name = req.get("name")
        state = req.get("state")
        out = []
        for rec in self.task_events.iter_recent():
            if name and rec["name"] != name:
                continue
            if state and rec["state"] != state:
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return out

    async def rpc_metrics_text(self, req):
        """Prometheus text over RPC: lets bench.py and tooling scrape
        the shard/scheduler counters without a metrics port."""
        return {"text": self._metrics_text()}

    async def rpc_dump_stacks(self, req):
        """All Python thread stacks of the GCS process (+ held-lock info
        when lockdep is armed) — the head-node contribution to
        `ray_tpu stack`, the distributed analog of `ray stack`."""
        return {"pid": os.getpid(), "role": "gcs",
                "threads": health_mod.dump_stacks()}

    async def rpc_get_cluster_load(self, req):
        """Aggregate demand/idleness snapshot for the autoscaler
        (reference: GcsAutoscalerStateManager::HandleGetClusterResourceState,
        autoscaler.proto)."""
        now = time.monotonic()
        nodes = []
        for node in self.nodes.values():
            if not node["alive"]:
                continue
            nodes.append({
                "node_id": node["node_id"],
                "total": node["total"],
                "available": node["available"],
                "labels": node.get("labels", {}),
                "idle_duration_s": (now - node["idle_since"]
                                    if "idle_since" in node else 0.0),
            })
        pending = []
        for node in self.nodes.values():
            if node["alive"]:
                pending.extend(node.get("pending_demands", []))
        # actors the GCS itself could not place yet
        for actor_id in self._pending_actors:
            if actor_id in self._scheduling_actors:
                continue  # lease already dispatched to a raylet — its
                # demand shows up there (or is being satisfied)
            info = self.actors.get(actor_id)
            if info is not None:
                pending.append(
                    task_mod.TaskSpec.from_wire(info["spec"]).resources)
        pending_pgs = []
        for pg_id in self._pending_pgs:
            pg = self.placement_groups.get(pg_id)
            if pg is not None and pg["state"] == "PENDING":
                pending_pgs.append({
                    "bundles": pg["bundles"],
                    "strategy": pg["strategy"],
                    "topology": pg.get("topology"),
                })
        return {"nodes": nodes, "pending": pending,
                "pending_pgs": pending_pgs}

    async def _health_check_loop(self):
        # Reference: GcsHealthCheckManager — mark nodes dead after missed
        # heartbeats; publish so raylets/workers fail fast.
        period = self.config.raylet_heartbeat_period_s
        threshold = self.config.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            if _fi._PLAN is not None:
                await _fi._PLAN.gcs_health_tick()
            now = time.monotonic()
            for node_id, node in list(self.nodes.items()):
                if not node["alive"]:
                    continue
                last = self._last_heartbeat.get(node_id, 0)
                if now - last > period * threshold:
                    await self._mark_node_dead(node_id, "missed heartbeats")

    async def _mark_node_dead(self, node_id: bytes, reason: str):
        node = self.nodes.get(node_id)
        if node is None or not node["alive"]:
            return
        node["alive"] = False
        self.view.remove_node(node_id)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        await export_events.report_async(
            "GCS", "ERROR", "NODE_DEAD",
            f"node {node_id.hex()[:8]} dead: {reason}",
            node_id=node_id.hex(), reason=reason)
        # raylet_addr rides the notice so owners can invalidate object
        # locations (keyed by raylet address) without a get_nodes round
        # trip per death
        await self.publish("nodes", {"event": "removed", "node_id": node_id,
                                     "raylet_addr": node.get("raylet_addr",
                                                             ""),
                                     "reason": reason})
        async with self._resources_pub_lock:
            await self.publish("resources", {"node_id": node_id,
                                             "dead": True})
        # Fail over actors that lived on that node.
        for actor_id, info in list(self.actors.items()):
            if info.get("node_id") == node_id and info["state"] in (ALIVE, PENDING):
                await self._on_actor_failure(actor_id, f"node died: {reason}")

    # ------------------------------------------------------------------
    # KV + function table (GcsKvManager / function_manager)
    # ------------------------------------------------------------------

    async def rpc_kv_put(self, req):
        ns_name = req.get("ns", "")
        ns = self.kv.setdefault(ns_name, {})
        key = req["key"]
        if not req.get("overwrite", True) and key in ns:
            return {"added": False}
        ns[key] = req["value"]
        self._persist(f"kv:{ns_name}", key, req["value"])
        return {"added": True}

    async def rpc_kv_get(self, req):
        value = self.kv.get(req.get("ns", ""), {}).get(req["key"])
        return {"value": value}

    async def rpc_kv_del(self, req):
        ns_name = req.get("ns", "")
        existed = self.kv.get(ns_name, {}).pop(req["key"], None)
        if existed is not None:
            self._unpersist(f"kv:{ns_name}", req["key"])
        return {"deleted": existed is not None}

    async def rpc_kv_keys(self, req):
        prefix = req.get("prefix", b"")
        ns = self.kv.get(req.get("ns", ""), {})
        return {"keys": [k for k in ns if k.startswith(prefix)]}

    async def rpc_kv_exists(self, req):
        return {"exists": req["key"] in self.kv.get(req.get("ns", ""), {})}

    # ------------------------------------------------------------------
    # jobs (GcsJobManager)
    # ------------------------------------------------------------------

    async def rpc_register_job(self, req):
        job_id = req["job_id"]
        self.jobs[job_id] = {
            "job_id": job_id,
            "driver_addr": req.get("driver_addr", ""),
            "start_time": time.time(),
            "finished": False,
            # per-job quota/weight dict (multi-tenant isolation plane);
            # fanned out to every raylet via the jobs channel and pulled
            # by late-joining raylets through list_jobs
            "quotas": req.get("quotas") or None,
        }
        self._persist("jobs", job_id, self.jobs[job_id])
        await self.publish("jobs", {"event": "started", "job_id": job_id,
                                    "quotas": req.get("quotas") or None})
        return {"ok": True}

    async def rpc_finish_job(self, req):
        job_id = req["job_id"]
        job = self.jobs.get(job_id)
        if job:
            job["finished"] = True
            job["end_time"] = time.time()
            self._persist("jobs", job_id, job)
        # Tear down the job's non-detached actors.
        for actor_id, info in list(self.actors.items()):
            if info["job_id"] == job_id and not info.get("detached") \
                    and info["state"] != DEAD:
                await self._kill_actor(actor_id, "job finished")
        await export_events.report_async(
            "GCS", "INFO", "JOB_FINISHED",
            f"job {job_id.hex()[:8]} finished", job_id=job_id.hex())
        await self.publish("jobs", {"event": "finished", "job_id": job_id})
        return {"ok": True}

    # ------------------------------------------------------------------
    # actors (GcsActorManager + GcsActorScheduler)
    # ------------------------------------------------------------------

    async def rpc_register_actor(self, req):
        spec = task_mod.TaskSpec.from_wire(req["spec"])
        actor_id = spec.actor_id
        if spec.actor_name:
            if spec.actor_name in self.named_actors:
                existing = self.named_actors[spec.actor_name]
                if self.actors[existing]["state"] != DEAD:
                    return {"ok": False,
                            "error": f"actor name taken: {spec.actor_name}"}
            self.named_actors[spec.actor_name] = actor_id
            self._persist("named_actors", spec.actor_name.encode(),
                          actor_id)
        self.actors[actor_id] = {
            "actor_id": actor_id,
            "job_id": spec.job_id,
            "name": spec.actor_name,
            "state": PENDING,
            "addr": None,
            "node_id": None,
            "spec": req["spec"],
            "max_restarts": spec.max_restarts,
            "num_restarts": 0,
            "detached": spec.detached,
            "death_cause": None,
            "class_name": spec.name,
        }
        self._persist("actors", actor_id, self.actors[actor_id])
        self._pending_actors.append(actor_id)
        self._retry_wakeup.set()
        return {"ok": True}

    async def _schedule_one(self, actor_id: bytes):
        try:
            done = await self._schedule_actor(actor_id)
        except Exception:
            logger.exception("actor scheduling error")
            done = False
        finally:
            self._scheduling_actors.discard(actor_id)
        if done and actor_id in self._pending_actors:
            self._pending_actors.remove(actor_id)

    async def _schedule_actor(self, actor_id: bytes) -> bool:
        info = self.actors.get(actor_id)
        if info is None or info["state"] not in (PENDING, RESTARTING):
            return True
        spec = task_mod.TaskSpec.from_wire(info["spec"])
        if spec.placement_group_id is not None:
            # PG-targeted actors are placed on the bundle's node.
            pg = self.placement_groups.get(spec.placement_group_id)
            if pg is None or pg["state"] != "CREATED":
                return False
            index = spec.bundle_index if spec.bundle_index >= 0 else 0
            node_id = pg["bundle_nodes"][index]
            node = next(
                (n for n in self.view.alive_nodes() if n.node_id == node_id),
                None,
            )
        else:
            node = pick_node(
                self.view, spec.resources, spec.strategy,
                target_node_id=spec.node_id,
                soft=spec.soft,
                spread_threshold=self.config.scheduler_spread_threshold,
            )
        if node is None:
            return False
        try:
            raylet = await self.clients.get(node.raylet_addr)
            lease = await raylet.call(
                "request_worker_lease",
                {"spec": info["spec"], "dedicated": True},
                timeout=60.0,
            )
        except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError) as e:
            logger.warning("actor lease failed on %s: %s", node.raylet_addr, e)
            return False
        if not lease.get("granted"):
            return False
        worker_addr = lease["worker_addr"]
        try:
            worker = await self.clients.get(worker_addr)
            reply = await worker.call("push_task", {"spec": info["spec"]},
                                      timeout=300.0)
            if reply.get("error"):
                raise RpcError(reply["error_msg"])
        except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError) as e:
            logger.warning("actor creation failed on %s: %s", worker_addr, e)
            info["death_cause"] = f"creation failed: {e}"
            info["state"] = DEAD
            # Release the dedicated lease and kill the contaminated worker,
            # or the node permanently loses those resources.
            try:
                await raylet.call("return_worker", {
                    "lease_id": lease["lease_id"],
                    "worker_dead": False,
                    "kill_worker": True,
                })
            except (ConnectionLost, RpcError, OSError):
                pass
            await self._publish_actor(actor_id)
            return True
        info["state"] = ALIVE
        info["addr"] = worker_addr
        info["node_id"] = node.node_id
        info["worker_id"] = lease.get("worker_id")
        await self._publish_actor(actor_id)
        return True

    async def _publish_actor(self, actor_id: bytes):
        info = self.actors[actor_id]
        # every actor state transition flows through here — the one
        # write-through hook actor durability needs
        self._persist("actors", actor_id, info)
        await self.publish("actors", {
            "actor_id": actor_id,
            "state": info["state"],
            "addr": info["addr"],
            "death_cause": info["death_cause"],
            "num_restarts": info["num_restarts"],
        })

    async def rpc_get_actor(self, req):
        actor_id = req.get("actor_id")
        if actor_id is None and req.get("name"):
            actor_id = self.named_actors.get(req["name"])
            if actor_id is None:
                return {"found": False}
        info = self.actors.get(actor_id)
        if info is None:
            return {"found": False}
        return {
            "found": True,
            "actor_id": actor_id,
            "state": info["state"],
            "addr": info["addr"],
            "spec": info["spec"],
            "death_cause": info["death_cause"],
            "num_restarts": info["num_restarts"],
            "name": info["name"],
            "class_name": info.get("class_name"),
        }

    async def rpc_list_actors(self, req):
        return [
            {
                "actor_id": a["actor_id"],
                "state": a["state"],
                "name": a["name"],
                "class_name": a.get("class_name"),
                "node_id": a.get("node_id"),
                "num_restarts": a["num_restarts"],
            }
            for a in self.actors.values()
        ]

    async def rpc_list_events(self, req):
        """Recent structured events, served from the GCS host's event
        dir (all daemons of a multi-node-on-one-machine cluster write
        there; remote-machine raylet events are not forwarded — same
        node-local scope as the reference's event agent). A short TTL
        cache bounds the re-read cost under dashboard polling."""
        now = time.time()
        cached = getattr(self, "_events_cache", None)
        if cached is not None and now - cached[0] < 2.0:
            return cached[1]
        merged = await asyncio.get_running_loop().run_in_executor(
            None, export_events.list_events)
        out = merged[-500:]
        self._events_cache = (now, out)
        return out

    async def rpc_list_jobs(self, req):
        return [
            {
                "job_id": jb["job_id"],
                "driver_addr": jb.get("driver_addr", ""),
                "start_time": jb.get("start_time"),
                "end_time": jb.get("end_time"),
                "finished": jb.get("finished", False),
                "quotas": jb.get("quotas"),
            }
            for jb in self.jobs.values()
        ]

    async def rpc_report_actor_death(self, req):
        await self._on_actor_failure(req["actor_id"], req.get("reason", "died"))
        return {"ok": True}

    async def _on_actor_failure(self, actor_id: bytes, reason: str):
        info = self.actors.get(actor_id)
        if info is None or info["state"] == DEAD:
            return
        restarts = info["max_restarts"]
        will_restart = restarts == -1 or info["num_restarts"] < restarts
        await export_events.report_async(
            "GCS", "WARNING",
            "ACTOR_RESTARTING" if will_restart else "ACTOR_DEAD",
            f"actor {actor_id.hex()[:8]} failed: {reason}",
            actor_id=actor_id.hex(), reason=reason,
            num_restarts=info["num_restarts"])
        if will_restart:
            info["num_restarts"] += 1
            info["state"] = RESTARTING
            info["addr"] = None
            await self._publish_actor(actor_id)
            self._pending_actors.append(actor_id)
            self._retry_wakeup.set()
        else:
            info["state"] = DEAD
            info["death_cause"] = reason
            info["addr"] = None
            await self._publish_actor(actor_id)

    async def _kill_actor(self, actor_id: bytes, reason: str):
        info = self.actors.get(actor_id)
        if info is None:
            return
        addr = info.get("addr")
        info["state"] = DEAD
        info["death_cause"] = reason
        info["max_restarts"] = 0
        if addr:
            try:
                worker = await self.clients.get(addr)
                # worker_id lets a virtual-worker raylet (which serves
                # many workers at one address) identify whose lease to
                # release; real workers ignore the extra field
                await worker.notify("exit_worker", {
                    "reason": reason,
                    "worker_id": info.get("worker_id"),
                })
            except (ConnectionLost, OSError, RpcError):
                pass
        await self._publish_actor(actor_id)

    async def rpc_kill_actor(self, req):
        await self._kill_actor(req["actor_id"], req.get("reason", "ray.kill"))
        return {"ok": True}

    # ------------------------------------------------------------------
    # placement groups (GcsPlacementGroupManager)
    # ------------------------------------------------------------------

    async def rpc_create_placement_group(self, req):
        pg_id = req["pg_id"]
        self.placement_groups[pg_id] = {
            "pg_id": pg_id,
            "bundles": req["bundles"],
            "strategy": req["strategy"],
            "name": req.get("name"),
            "state": "PENDING",
            "bundle_nodes": [],
            "job_id": req.get("job_id"),
            # TPU pod-slice topology (e.g. "v4-16"): bundles gang-place
            # one-per-host onto a single complete slice, atomically
            "topology": req.get("topology"),
        }
        self._persist("placement_groups", pg_id,
                      self.placement_groups[pg_id])
        self._pending_pgs.append(pg_id)
        self._retry_wakeup.set()
        return {"ok": True}

    async def _schedule_pg(self, pg_id: bytes) -> bool:
        pg = self.placement_groups.get(pg_id)
        if pg is None or pg["state"] != "PENDING":
            return True
        if pg.get("topology"):
            placement = place_slice_bundles(self.view, pg["bundles"],
                                            pg["topology"])
        else:
            placement = place_bundles(self.view, pg["bundles"],
                                      pg["strategy"])
        if placement is None:
            return False
        # Two-phase commit: prepare on every raylet, then commit (reference:
        # GcsPlacementGroupScheduler prepare/commit protocol).
        prepared = []
        ok = True
        for index, (node, demand) in enumerate(zip(placement, pg["bundles"])):
            try:
                raylet = await self.clients.get(node.raylet_addr)
                reply = await raylet.call("prepare_bundle", {
                    "pg_id": pg_id, "bundle_index": index, "resources": demand,
                }, timeout=10.0)
                if not reply.get("ok"):
                    ok = False
                    break
                prepared.append((node, index))
            except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError):
                ok = False
                break
        if not ok:
            for node, index in prepared:
                try:
                    raylet = await self.clients.get(node.raylet_addr)
                    await raylet.call("release_bundle",
                                      {"pg_id": pg_id, "bundle_index": index})
                except (ConnectionLost, RpcError, OSError):
                    pass
            return False
        for node, index in prepared:
            raylet = await self.clients.get(node.raylet_addr)
            await raylet.call("commit_bundle",
                              {"pg_id": pg_id, "bundle_index": index})
        pg["state"] = "CREATED"
        pg["bundle_nodes"] = [n.node_id for n in placement]
        self._persist("placement_groups", pg_id, pg)
        await self.publish("placement_groups", {
            "pg_id": pg_id, "state": "CREATED",
            "bundle_nodes": pg["bundle_nodes"],
        })
        return True

    async def rpc_get_placement_group(self, req):
        pg = self.placement_groups.get(req["pg_id"])
        if pg is None:
            return {"found": False}
        return {"found": True, **{k: v for k, v in pg.items()}}

    async def rpc_remove_placement_group(self, req):
        pg = self.placement_groups.get(req["pg_id"])
        if pg is None:
            return {"ok": True}
        for index, node_id in enumerate(pg.get("bundle_nodes", [])):
            node = self.nodes.get(node_id)
            if node and node["alive"]:
                try:
                    raylet = await self.clients.get(node["raylet_addr"])
                    await raylet.call(
                        "release_bundle",
                        {"pg_id": pg["pg_id"], "bundle_index": index},
                    )
                except (ConnectionLost, RpcError, OSError):
                    pass
        pg["state"] = "REMOVED"
        self._persist("placement_groups", pg["pg_id"], pg)
        await self.publish("placement_groups",
                           {"pg_id": pg["pg_id"], "state": "REMOVED"})
        return {"ok": True}

    # ------------------------------------------------------------------
    # pending-work retry loop (actor + PG scheduling)
    # ------------------------------------------------------------------

    async def _retry_loop(self):
        while True:
            try:
                await asyncio.wait_for(self._retry_wakeup.wait(), timeout=0.5)
            except asyncio.TimeoutError:
                pass
            self._retry_wakeup.clear()
            if self._pending_actors:
                # Dispatch concurrently: one slow actor __init__ must not
                # head-of-line block every other creation.
                for actor_id in list(self._pending_actors):
                    if actor_id in self._scheduling_actors:
                        continue
                    self._scheduling_actors.add(actor_id)
                    asyncio.ensure_future(self._schedule_one(actor_id))
            if self._pending_pgs:
                still_pgs: List[bytes] = []
                for pg_id in self._pending_pgs:
                    try:
                        done = await self._schedule_pg(pg_id)
                    except Exception:  # noqa: BLE001
                        # one malformed request must never kill the
                        # scheduler loop for the whole cluster
                        logger.exception("PG %s scheduling failed",
                                         pg_id.hex()[:8])
                        done = False
                    if not done:
                        still_pgs.append(pg_id)
                self._pending_pgs = still_pgs


async def main(host: str, port: int, metrics_port=None,
               daemonize: bool = False, persist_path=None,
               store_path=None):
    import os
    import signal

    _fi.set_role("gcs")  # arm gcs-scoped timed faults (offsets from now)
    # snapshot load is one-time startup I/O before the server accepts
    # its first connection — the loop has nothing else to run yet
    server = GcsServer(host, port, persist_path=persist_path,  # raylint: disable=async-blocking
                       store_path=store_path)
    await server.start(metrics_port=metrics_port)
    print(f"GCS_READY {server.address}", flush=True)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    loop.add_signal_handler(signal.SIGTERM, stop.set)
    loop.add_signal_handler(signal.SIGINT, stop.set)

    async def parent_watch():
        # Exit if the spawning driver dies (see raylet main's parent_watch).
        parent = os.getppid()
        while os.getppid() == parent:
            await asyncio.sleep(1.0)
        stop.set()

    if not daemonize:
        asyncio.ensure_future(parent_watch())
    await stop.wait()
    await server.stop()


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--metrics-port", type=int, default=None)
    parser.add_argument("--persist-path", default=None,
                        help="snapshot file for GCS fault tolerance")
    parser.add_argument("--store-path", default=None,
                        help="write-through StoreClient dir (file-per-"
                             "key Redis-role backend; fresher than "
                             "snapshots)")
    parser.add_argument("--log-file", default=None)
    parser.add_argument("--daemonize", action="store_true",
                        help="survive the launching process (CLI mode)")
    args = parser.parse_args()
    if args.log_file:
        logging.basicConfig(filename=args.log_file, level=logging.INFO)
    asyncio.run(main(args.host, args.port, args.metrics_port,
                     daemonize=args.daemonize,
                     persist_path=args.persist_path,
                     store_path=args.store_path))
