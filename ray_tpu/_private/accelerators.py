"""TPU accelerator abstraction: chip detection + pod-slice topology.

Reference: `python/ray/_private/accelerators/tpu.py:75` —
`TPUAcceleratorManager` detects chips via `/dev/accel*` (:104-120), reads
pod topology from instance metadata (:199), advertises `TPU-{version}`
accelerator resources (:312-315) and a one-per-slice
`TPU-{pod_type}-head` resource on worker 0 (:363-388).

TPU-first delta: the reference leaves the head-resource convention to
user code (fan out one task per host by hand, doc comment tpu.py:341-369).
Here the slice is promoted into the scheduler itself — raylets carry
slice labels, and the GCS places slice-topology placement groups
atomically (see `scheduling.place_slice_bundles`) — so gang scheduling a
pod slice is a first-class primitive, not a convention.

Slice metadata comes from env vars (set by the TPU-VM runtime or by the
test Cluster): `TPU_ACCELERATOR_TYPE` (e.g. "v4-16"), `TPU_WORKER_ID`
(host index in the slice), `TPU_SLICE_NAME` (unique slice identity;
falls back to the pod name), `TPU_WORKER_HOSTNAMES` (to count hosts).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional

# label keys carried by every raylet in a slice
LABEL_SLICE_NAME = "ray_tpu.slice_name"
LABEL_SLICE_TYPE = "ray_tpu.slice_type"
LABEL_SLICE_HOST_ID = "ray_tpu.slice_host_id"
LABEL_SLICE_NUM_HOSTS = "ray_tpu.slice_num_hosts"


def apply_jax_platforms(platforms: Optional[str]) -> None:
    """Make a JAX_PLATFORMS assignment effective even when a site hook
    pre-imported jax with an accelerator backend as the default (the env
    var is only read at first import). No-op when jax is not yet
    imported — first import will read the env var itself."""
    import sys

    if platforms and "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", platforms)
        except Exception:  # noqa: BLE001 — backend may be finalized
            pass


def num_local_chips() -> int:
    """Detect this host's TPU chip count (reference tpu.py:104-120:
    /dev/accel* then /dev/vfio; env override first for tests)."""
    env = os.environ.get("TPU_CHIP_COUNT")
    if env:
        return int(env)
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def head_resource_name(slice_type: str) -> str:
    """`TPU-{pod_type}-head` (reference tpu.py:363)."""
    return f"TPU-{slice_type}-head"


def slice_env() -> Optional[Dict[str, str]]:
    """Slice membership labels for this host, or None when the host is
    not part of a TPU pod slice."""
    slice_type = os.environ.get("TPU_ACCELERATOR_TYPE")
    if not slice_type:
        return None
    host_id = int(os.environ.get("TPU_WORKER_ID", "0"))
    name = os.environ.get("TPU_SLICE_NAME") or \
        os.environ.get("TPU_NAME") or f"slice-{slice_type}"
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    num_hosts = len(hostnames.split(",")) if hostnames else 1
    return {
        LABEL_SLICE_NAME: name,
        LABEL_SLICE_TYPE: slice_type,
        LABEL_SLICE_HOST_ID: str(host_id),
        LABEL_SLICE_NUM_HOSTS: str(num_hosts),
    }


def slice_resources(labels: Dict[str, str]) -> Dict[str, float]:
    """Extra resources a raylet derives from its slice labels: host 0
    carries the one-per-slice head resource so a driver can target "one
    task per slice" exactly as in the reference convention."""
    if labels.get(LABEL_SLICE_TYPE) is None:
        return {}
    if int(labels.get(LABEL_SLICE_HOST_ID, "0")) != 0:
        return {}
    return {head_resource_name(labels[LABEL_SLICE_TYPE]): 1.0}
