"""Runtime environments: per-task/actor env vars + code shipping.

Reference: `python/ray/runtime_env/runtime_env.py:152` (the RuntimeEnv
spec) and `python/ray/_private/runtime_env/{working_dir,py_modules}.py`
(URI-addressed packages installed by the per-node agent). Here the
packages live in the GCS KV (content-addressed zips) and the WORKER
materializes them at startup — no separate agent process; the raylet
pools workers per runtime-env hash exactly like the reference's
per-runtime-env worker pools (worker_pool.h:159).

Supported fields: `env_vars` (dict), `working_dir` (local dir, shipped
and chdir'd), `py_modules` (list of local dirs, shipped and put on
sys.path).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Dict, List, Optional

_KV_NS = "runtime_env"
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                zf.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(cap {_MAX_PACKAGE_BYTES})")
    return data


def prepare(cw, runtime_env: Dict) -> Dict:
    """Driver-side: upload local dirs to the GCS KV (content-addressed)
    and return the wire form carried in TaskSpec.runtime_env."""
    wire: Dict = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        wire["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}

    def upload(path: str) -> str:
        data = _zip_dir(path)
        key = hashlib.sha1(data).hexdigest()[:20]
        cw._run_sync(cw.gcs.call("kv_put", {
            "ns": _KV_NS, "key": key.encode(), "value": data,
            "overwrite": False,
        }))
        return key

    if runtime_env.get("working_dir"):
        wire["working_dir"] = upload(runtime_env["working_dir"])
    if runtime_env.get("py_modules"):
        wire["py_modules"] = [
            {"key": upload(p), "name": os.path.basename(p.rstrip("/"))}
            for p in runtime_env["py_modules"]
        ]
    unknown = set(runtime_env) - {"env_vars", "working_dir", "py_modules"}
    if unknown:
        raise ValueError(f"unsupported runtime_env fields: {unknown}")
    # precompute the pooling identity once: scheduling_key() reads it on
    # every submit, which must not pay a json+sha1 per task
    wire["_hash"] = hashlib.sha1(
        json.dumps(wire, sort_keys=True).encode()).hexdigest()[:16]
    return wire


def env_hash(wire: Optional[Dict]) -> str:
    """Stable identity for worker pooling; empty env hashes to ''."""
    if not wire:
        return ""
    cached = wire.get("_hash")
    if cached is not None:
        return cached
    return hashlib.sha1(
        json.dumps(wire, sort_keys=True).encode()).hexdigest()[:16]


def materialize(cw, wire: Dict, target_root: str) -> None:
    """Worker-side: download + extract packages, apply sys.path/cwd.
    env_vars were already applied by the raylet at spawn."""
    os.makedirs(target_root, exist_ok=True)

    def fetch_extract(key: str, subdir: str) -> str:
        dest = os.path.join(target_root, subdir)
        if not os.path.isdir(dest):
            reply = cw._run_sync(cw.gcs.call("kv_get", {
                "ns": _KV_NS, "key": key.encode()}))
            data = reply["value"]
            if data is None:
                raise RuntimeError(f"runtime_env package {key} missing")
            # per-process tmp: concurrent workers materializing the same
            # env must not collide; whoever renames first wins, the
            # loser's rename failure is success (dest exists)
            tmp = f"{dest}.tmp.{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:
                if not os.path.isdir(dest):
                    raise
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        return dest

    for mod in wire.get("py_modules", []):
        dest = fetch_extract(mod["key"], f"mod-{mod['key']}")
        # a module dir is importable by its own name: expose its parent
        parent = os.path.join(target_root, f"modroot-{mod['key']}")
        os.makedirs(parent, exist_ok=True)
        link = os.path.join(parent, mod["name"])
        try:
            os.symlink(dest, link)
        except FileExistsError:
            pass  # a concurrent worker won the race — same target
        if parent not in sys.path:
            sys.path.insert(0, parent)
    if wire.get("working_dir"):
        dest = fetch_extract(wire["working_dir"], f"wd-{wire['working_dir']}")
        os.chdir(dest)
        if dest not in sys.path:
            sys.path.insert(0, dest)
