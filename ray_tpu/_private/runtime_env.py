"""Runtime environments: per-task/actor env vars + code shipping + pip.

Reference: `python/ray/runtime_env/runtime_env.py:152` (the RuntimeEnv
spec) and `python/ray/_private/runtime_env/{working_dir,py_modules,
pip}.py` (URI-addressed packages installed by the per-node agent). Here
the packages live in the GCS KV (content-addressed zips) and the WORKER
materializes them at startup — no separate agent process; the raylet
pools workers per runtime-env hash exactly like the reference's
per-runtime-env worker pools (worker_pool.h:159).

Supported fields: `env_vars` (dict), `working_dir` (local dir, shipped
and chdir'd), `py_modules` (list of local dirs, shipped and put on
sys.path), `pip` (requirements list / requirements.txt path / dict with
`packages` + `install_options`) — the raylet builds a content-addressed
cached venv per requirements set (reference `pip.py` URI caching) and
launches the pool's workers from the venv interpreter.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import subprocess
import sys
import zipfile
from typing import Dict, List, Optional

_KV_NS = "runtime_env"
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


# ---------------------------------------------------------------------------
# plugin seam (reference: python/ray/_private/runtime_env/plugin.py —
# RuntimeEnvPlugin ABC + the RAY_RUNTIME_ENV_PLUGINS registration env
# var). A plugin owns one runtime_env FIELD: it validates/uploads on the
# driver and materializes on the worker. The built-in fields
# (env_vars/working_dir/py_modules/pip) are handled natively below; any
# OTHER field must have a registered plugin — the seam where a
# container/hermetic-image backend slots in (zero-egress environments
# get no container plugin by default, but the extension point is load-
# bearing and tested).
# ---------------------------------------------------------------------------


class RuntimeEnvPlugin:
    """Owns one runtime_env field (`name`). Driver side: `prepare`
    validates the user value and returns its wire form (uploading any
    payloads — `upload(path) -> key` stores into the GCS KV). Worker
    side: `materialize` applies the wire value before any task runs
    (chdir, sys.path, env vars via os.environ)."""

    name: str = ""

    def prepare(self, value, upload) -> Any:
        return value

    def materialize(self, value, fetch, target_root: str) -> None:
        raise NotImplementedError


_plugins: Dict[str, RuntimeEnvPlugin] = {}
_env_plugins_loaded = False


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin must set a field name")
    _plugins[plugin.name] = plugin


def _load_env_plugins() -> None:
    """One-time load of plugins named in RAY_TPU_RUNTIME_ENV_PLUGINS
    ("module:Class,module:Class" — the reference's env-var registration
    mechanism). Runs on both driver and worker, so a plugin's two
    halves resolve symmetrically."""
    global _env_plugins_loaded
    if _env_plugins_loaded:
        return
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        mod_name, _, cls_name = entry.partition(":")
        import importlib

        cls = getattr(importlib.import_module(mod_name), cls_name)
        register_plugin(cls())
    # marked loaded only after EVERY entry imported: a bad entry must
    # surface on each attempt, not silently freeze a partial registry
    _env_plugins_loaded = True


# ---------------------------------------------------------------------------
# pip venv isolation (reference python/ray/_private/runtime_env/pip.py)
# ---------------------------------------------------------------------------


def normalize_pip(pip) -> Dict:
    """Driver-side normalization of the `pip` field to its wire form:
    {"packages": [...], "install_options": [...]}. Accepts a requirements
    list, a requirements.txt path, or the dict form."""
    if isinstance(pip, str):
        with open(pip) as f:
            pkgs = [ln.strip() for ln in f
                    if ln.strip() and not ln.strip().startswith("#")]
        bad = [p for p in pkgs if p.startswith("-")]
        if bad:
            # directive lines reference driver-local files / global pip
            # state that won't exist on the node building the venv
            raise ValueError(
                f"requirements directives are not supported: {bad}; "
                "pass plain requirement specs, with pip flags in "
                '{"packages": [...], "install_options": [...]} form')
        return {"packages": pkgs, "install_options": []}
    if isinstance(pip, (list, tuple)):
        return {"packages": [str(p) for p in pip], "install_options": []}
    if isinstance(pip, dict):
        unknown = set(pip) - {"packages", "install_options"}
        if unknown:
            raise ValueError(f"unsupported pip fields: {unknown}")
        return {"packages": [str(p) for p in pip.get("packages", [])],
                "install_options": [str(o) for o in
                                    pip.get("install_options", [])]}
    raise TypeError(f"runtime_env pip must be list/str/dict, got {pip!r}")


def pip_env_cache_root() -> str:
    return os.environ.get("RAY_TPU_PIP_ENV_CACHE",
                          "/tmp/ray_tpu/pip_envs")


class RuntimeEnvSetupError(RuntimeError):
    pass


# Per-process build coordination: one thread builds a given env while
# others wait, and a deterministic failure is remembered so a queue of
# tasks with a broken spec doesn't re-run the failing install per lease.
import threading as _threading

_pip_build_lock = _threading.Lock()
_pip_key_locks: Dict[str, _threading.Lock] = {}
_pip_failed: Dict[str, str] = {}

_PIP_CACHE_MAX_ENVS = int(os.environ.get("RAY_TPU_PIP_ENV_CACHE_MAX", "10"))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else


def _venv_in_use(path: str) -> bool:
    """True if any live process holds an `.inuse.<pid>` marker on this
    venv (written by `mark_pip_env_in_use`). Stale markers from dead
    processes are pruned as a side effect."""
    in_use = False
    try:
        for name in os.listdir(path):
            if not name.startswith(".inuse."):
                continue
            try:
                pid = int(name.rsplit(".", 1)[1])
            except ValueError:
                continue
            if _pid_alive(pid):
                in_use = True
            else:
                try:
                    os.unlink(os.path.join(path, name))
                except OSError:
                    pass
    except OSError:
        pass
    return in_use


def mark_pip_env_in_use(dest: str) -> None:
    """Pin a venv against LRU eviction for this process's lifetime.
    Called by each pooled WORKER running from the venv interpreter
    (worker_main) — the pin lasts exactly as long as the workers that
    need the env, and crashes self-clean because the marker is checked
    against pid liveness (the reference refcounts URIs and deletes only
    on release — `runtime_env/pip.py`)."""
    try:
        open(os.path.join(dest, f".inuse.{os.getpid()}"), "w").close()
    except OSError:
        pass


def _evict_pip_cache(root: str, keep: str) -> None:
    """Bound the venv cache: beyond the cap, drop the least-recently-used
    entries (.ready mtime is touched on reuse) — skipping venvs whose
    interpreter is still backing a live process's worker pool. The
    reference refcounts URIs and deletes on release; an LRU cap with
    liveness pins is the agentless equivalent."""
    try:
        entries = [d for d in os.listdir(root)
                   if d != keep and ".tmp." not in d
                   and os.path.exists(os.path.join(root, d, ".ready"))]
        if len(entries) + 1 <= _PIP_CACHE_MAX_ENVS:
            return
        entries.sort(key=lambda d: os.path.getmtime(
            os.path.join(root, d, ".ready")))
        excess = len(entries) + 1 - _PIP_CACHE_MAX_ENVS
        for d in entries:
            if excess <= 0:
                break
            full = os.path.join(root, d)
            if _venv_in_use(full):
                continue  # live workers run from this interpreter
            shutil.rmtree(full, ignore_errors=True)
            excess -= 1
    except OSError:
        pass


def ensure_pip_env(pip_wire: Dict) -> str:
    """Build (or reuse) the cached venv for a requirements set; returns
    the venv interpreter path. Content-addressed by the normalized pip
    spec, so every job/worker with the same requirements shares one venv
    (reference pip.py URI caching). Safe under concurrent builders: each
    builds in a private tmp dir and the first atomic rename wins.

    The venv inherits the base interpreter's site-packages
    (--system-site-packages) so ray_tpu and its deps stay importable;
    pip resolves from the inherited site-packages, with an ensurepip
    bootstrap fallback for bases that carry no pip."""
    key = hashlib.sha1(json.dumps(
        pip_wire, sort_keys=True).encode()).hexdigest()[:20]
    root = pip_env_cache_root()
    dest = os.path.join(root, key)
    py = os.path.join(dest, "bin", "python")
    ready = os.path.join(dest, ".ready")
    with _pip_build_lock:
        key_lock = _pip_key_locks.setdefault(key, _threading.Lock())
    with key_lock:  # one builder per env per process; others wait here
        if key in _pip_failed:
            raise RuntimeEnvSetupError(_pip_failed[key])
        if os.path.exists(ready):
            try:
                os.utime(ready)  # LRU touch
            except OSError:
                pass
            return py
        try:
            # holding key_lock across the build is the point: it is the
            # per-env stripe that makes concurrent requesters wait for
            # one builder # raylint: disable=blocking-under-lock
            return _build_pip_env(pip_wire, root, dest, py, ready)
        except RuntimeEnvSetupError as e:
            _pip_failed[key] = str(e)
            raise


def _build_pip_env(pip_wire: Dict, root: str, dest: str, py: str,
                   ready: str) -> str:
    os.makedirs(root, exist_ok=True)
    # uuid component: unique across threads AND processes (pid alone
    # collides for two executor threads of one raylet)
    tmp = f"{dest}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
    try:
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             "--without-pip", tmp],
            check=True, capture_output=True, timeout=120)
        # --system-site-packages exposes the ROOT interpreter's site dirs;
        # when the building interpreter is itself a venv (common: /opt
        # installs), its packages — ray_tpu's own deps — would be lost.
        # A .pth appends the builder's site dirs AFTER the new venv's own
        # site-packages, so pip-installed packages still shadow them.
        import glob as _glob
        import site as _site
        venv_sites = _glob.glob(
            os.path.join(tmp, "lib", "python*", "site-packages"))
        if venv_sites:
            with open(os.path.join(venv_sites[0], "_ray_tpu_base.pth"),
                      "w") as f:
                for p in _site.getsitepackages():
                    f.write(p + "\n")
        pkgs = pip_wire.get("packages", [])
        if pkgs:
            # `--without-pip` + `-m pip` rides the builder interpreter's
            # site-packages pip (exposed via --system-site-packages).
            # When the base install has no importable pip, bootstrap one
            # into the venv with ensurepip and retry — instead of failing
            # every pip runtime_env on such bases.
            install = ["-m", "pip", "install", "--quiet",
                       "--disable-pip-version-check",
                       *pip_wire.get("install_options", []), *pkgs]
            venv_py = os.path.join(tmp, "bin", "python")
            res = subprocess.run([venv_py, *install],
                                 capture_output=True, timeout=600)
            if (res.returncode != 0
                    and b"No module named pip" in res.stderr):
                boot = subprocess.run(
                    [venv_py, "-m", "ensurepip", "--upgrade"],
                    capture_output=True, timeout=300)
                if boot.returncode == 0:
                    res = subprocess.run([venv_py, *install],
                                         capture_output=True, timeout=600)
            if res.returncode != 0:
                raise RuntimeEnvSetupError(
                    "pip install failed for runtime_env "
                    f"{pkgs}: {res.stderr.decode(errors='replace')[-2000:]}")
        open(os.path.join(tmp, ".ready"), "w").close()
        try:
            os.rename(tmp, dest)
        except OSError:
            # a concurrent builder won the rename — same content, fine
            if not os.path.exists(ready):
                raise
        _evict_pip_cache(root, keep=os.path.basename(dest))
    except subprocess.CalledProcessError as e:
        raise RuntimeEnvSetupError(
            f"venv creation failed: {e.stderr.decode(errors='replace')}")
    except subprocess.TimeoutExpired as e:
        # a deterministic-enough failure: surface it instead of letting
        # the raylet treat it as transient and loop the full install
        raise RuntimeEnvSetupError(f"pip env build timed out: {e.cmd}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return py


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                zf.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(cap {_MAX_PACKAGE_BYTES})")
    return data


def prepare(cw, runtime_env: Dict) -> Dict:
    """Driver-side: upload local dirs to the GCS KV (content-addressed)
    and return the wire form carried in TaskSpec.runtime_env."""
    wire: Dict = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        wire["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}

    def upload(path: str) -> str:
        data = _zip_dir(path)
        key = hashlib.sha1(data).hexdigest()[:20]
        cw._run_sync(cw.gcs.call("kv_put", {
            "ns": _KV_NS, "key": key.encode(), "value": data,
            "overwrite": False,
        }))
        return key

    if runtime_env.get("working_dir"):
        wire["working_dir"] = upload(runtime_env["working_dir"])
    if runtime_env.get("py_modules"):
        wire["py_modules"] = [
            {"key": upload(p), "name": os.path.basename(p.rstrip("/"))}
            for p in runtime_env["py_modules"]
        ]
    if runtime_env.get("pip"):
        wire["pip"] = normalize_pip(runtime_env["pip"])
    if runtime_env.get("conda"):
        if runtime_env.get("pip"):
            raise ValueError(
                "runtime_env cannot set both pip and conda (reference "
                "semantics: pip installs INTO a conda env via the "
                "spec's own pip section)")
        wire["conda"] = normalize_conda(runtime_env["conda"])
    _load_env_plugins()
    unknown = set(runtime_env) - {"env_vars", "working_dir", "py_modules",
                                  "pip", "conda"}
    for field_name in sorted(unknown):
        plugin = _plugins.get(field_name)
        if plugin is None:
            raise ValueError(
                f"unsupported runtime_env field {field_name!r} (no "
                f"registered plugin; see runtime_env.register_plugin / "
                f"RAY_TPU_RUNTIME_ENV_PLUGINS)")
        wire[field_name] = plugin.prepare(runtime_env[field_name], upload)
    # precompute the pooling identity once: scheduling_key() reads it on
    # every submit, which must not pay a json+sha1 per task
    wire["_hash"] = hashlib.sha1(
        json.dumps(wire, sort_keys=True).encode()).hexdigest()[:16]
    return wire


def merge_wire(base: Dict, override: Dict) -> Dict:
    """Field-wise inheritance of prepared (wire-form) runtime envs: the
    override's fields win, `env_vars` merge key-wise, and the pooling
    hash is recomputed for the combined env (reference semantics:
    `python/ray/_private/runtime_env/validation.py` parent/child merge).
    """
    merged = {k: v for k, v in base.items() if k != "_hash"}
    for k, v in override.items():
        if k == "_hash":
            continue
        if k == "env_vars":
            ev = dict(merged.get("env_vars") or {})
            ev.update(v or {})
            merged[k] = ev
        else:
            merged[k] = v
    if merged.get("pip") and merged.get("conda"):
        # prepare() validates single env dicts only; the merge can still
        # combine a job-level conda with a per-actor pip (or vice versa),
        # and the raylet's spawn path would silently prefer pip. The
        # reference raises on the combination — so do we.
        raise ValueError(
            "merged runtime_env cannot set both pip and conda (job-level "
            "and per-actor/task envs combined to a pip+conda env; "
            "reference semantics: pip installs INTO a conda env via the "
            "spec's own pip section)")
    merged["_hash"] = hashlib.sha1(
        json.dumps(merged, sort_keys=True).encode()).hexdigest()[:16]
    return merged


def env_hash(wire: Optional[Dict]) -> str:
    """Stable identity for worker pooling; empty env hashes to ''."""
    if not wire:
        return ""
    cached = wire.get("_hash")
    if cached is not None:
        return cached
    return hashlib.sha1(
        json.dumps(wire, sort_keys=True).encode()).hexdigest()[:16]


def materialize(cw, wire: Dict, target_root: str) -> None:
    """Worker-side: download + extract packages, apply sys.path/cwd.
    env_vars were already applied by the raylet at spawn."""
    os.makedirs(target_root, exist_ok=True)

    def fetch_extract(key: str, subdir: str) -> str:
        dest = os.path.join(target_root, subdir)
        if not os.path.isdir(dest):
            reply = cw._run_sync(cw.gcs.call("kv_get", {
                "ns": _KV_NS, "key": key.encode()}))
            data = reply["value"]
            if data is None:
                raise RuntimeError(f"runtime_env package {key} missing")
            # per-process tmp: concurrent workers materializing the same
            # env must not collide; whoever renames first wins, the
            # loser's rename failure is success (dest exists)
            tmp = f"{dest}.tmp.{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(data)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:
                if not os.path.isdir(dest):
                    raise
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        return dest

    for mod in wire.get("py_modules", []):
        dest = fetch_extract(mod["key"], f"mod-{mod['key']}")
        # a module dir is importable by its own name: expose its parent
        parent = os.path.join(target_root, f"modroot-{mod['key']}")
        os.makedirs(parent, exist_ok=True)
        link = os.path.join(parent, mod["name"])
        try:
            os.symlink(dest, link)
        except FileExistsError:
            pass  # a concurrent worker won the race — same target
        if parent not in sys.path:
            sys.path.insert(0, parent)
    if wire.get("working_dir"):
        dest = fetch_extract(wire["working_dir"], f"wd-{wire['working_dir']}")
        os.chdir(dest)
        if dest not in sys.path:
            sys.path.insert(0, dest)

    _load_env_plugins()

    def fetch(key: str) -> bytes:
        reply = cw._run_sync(cw.gcs.call("kv_get", {
            "ns": _KV_NS, "key": key.encode()}))
        if reply["value"] is None:
            raise RuntimeError(f"runtime_env payload {key} missing")
        return reply["value"]

    # pip and conda are applied at SPAWN time (the raylet launches the
    # worker from the env's interpreter) — nothing to materialize here
    builtin = {"env_vars", "working_dir", "py_modules", "pip", "conda",
               "_hash"}
    for field_name in wire:
        if field_name in builtin:
            continue
        plugin = _plugins.get(field_name)
        if plugin is None:
            # iterate WIRE fields, not registered plugins: a field the
            # driver validated but this worker cannot apply must FAIL
            # the env setup, never silently run the task without its
            # declared environment (ship the plugin module via
            # py_modules + RAY_TPU_RUNTIME_ENV_PLUGINS)
            raise RuntimeError(
                f"runtime_env field {field_name!r} has no registered "
                f"plugin in this worker (set RAY_TPU_RUNTIME_ENV_PLUGINS "
                f"in env_vars and ship the module via py_modules)")
        plugin.materialize(wire[field_name], fetch, target_root)


# ---------------------------------------------------------------------------
# conda env isolation (reference python/ray/_private/runtime_env/conda.py:
# per-spec conda envs created by the agent, cached and reused). The worker
# interpreter comes FROM the env, so this is a native field like pip —
# the plugin seam cannot swap an already-running interpreter.
# ---------------------------------------------------------------------------


def normalize_conda(conda) -> Dict:
    """Driver-side normalization: an existing env NAME, a path to an
    environment.yml, or an inline spec dict (the yml's content)."""
    if isinstance(conda, str):
        if conda.endswith((".yml", ".yaml")):
            import yaml

            with open(conda) as f:
                spec = yaml.safe_load(f)
            if not isinstance(spec, dict):
                raise ValueError(f"malformed conda spec file {conda!r}")
            return {"spec": spec}
        return {"name": conda}
    if isinstance(conda, dict):
        return {"spec": conda}
    raise TypeError(
        f"runtime_env conda must be an env name, a spec file path, or a "
        f"spec dict, got {conda!r}")


def conda_env_cache_root() -> str:
    return os.environ.get("RAY_TPU_CONDA_ENV_CACHE",
                          "/tmp/ray_tpu/conda_envs")


def _conda_exe() -> str:
    exe = os.environ.get("RAY_TPU_CONDA_EXE") or shutil.which("conda")
    if not exe or not (os.path.isfile(exe) and os.access(exe, os.X_OK)):
        # deterministic failure — a missing binary must fail the waiting
        # leases, not leave the raylet respawning/hanging
        raise RuntimeEnvSetupError(
            "runtime_env requests a conda env but no usable conda "
            f"executable is available on this node (looked at {exe!r}; "
            "install conda or set RAY_TPU_CONDA_EXE)")
    return exe


_conda_build_lock = _threading.Lock()
_conda_key_locks: Dict[str, _threading.Lock] = {}
_conda_failed: Dict[str, str] = {}


_conda_named_cache: Dict[str, str] = {}


def ensure_conda_env(conda_wire: Dict) -> str:
    """Resolve (building if needed) the conda env for a wire spec;
    returns the env's python interpreter path. Spec envs are
    content-addressed by the normalized spec and cached like pip venvs;
    named envs resolve through `conda run` (once per name — the mapping
    is stable for the node's lifetime, and a per-spawn subprocess would
    tax every worker of the pool)."""
    exe = _conda_exe()
    if conda_wire.get("name"):
        name = conda_wire["name"]
        cached = _conda_named_cache.get(name)
        if cached:
            return cached
        try:
            out = subprocess.run(
                [exe, "run", "-n", name, "python", "-c",
                 "import sys; print(sys.executable)"],
                check=True, capture_output=True, text=True, timeout=120)
        except (subprocess.CalledProcessError,
                subprocess.TimeoutExpired, OSError) as e:
            stderr = getattr(e, "stderr", "") or ""
            raise RuntimeEnvSetupError(
                f"conda env {name!r} not usable: "
                f"{stderr[-500:] or e}") from e
        lines = out.stdout.strip().splitlines()
        if not lines:
            # `conda run` exiting 0 with empty stdout must be a
            # deterministic setup failure: anything else (IndexError)
            # reads as transient, and the raylet would respawn forever
            # while the waiting leases hang
            raise RuntimeEnvSetupError(
                f"conda env {name!r}: `conda run` produced no interpreter "
                f"path (stderr: {(out.stderr or '').strip()[-500:] or 'empty'})")
        py = lines[-1]
        _conda_named_cache[name] = py
        return py
    spec = conda_wire["spec"]
    key = hashlib.sha1(json.dumps(
        spec, sort_keys=True).encode()).hexdigest()[:20]
    dest = os.path.join(conda_env_cache_root(), key)
    py = os.path.join(dest, "bin", "python")
    ready = os.path.join(dest, ".ready")
    with _conda_build_lock:
        key_lock = _conda_key_locks.setdefault(key, _threading.Lock())
    with key_lock:
        if key in _conda_failed:
            raise RuntimeEnvSetupError(_conda_failed[key])
        if os.path.exists(ready):
            try:
                os.utime(ready)
            except OSError:
                pass
            return py
        try:
            # per-env stripe held across the build by design (one
            # builder, everyone else waits)
            # raylint: disable=blocking-under-lock
            return _build_conda_env(exe, spec, dest, py, ready)
        except RuntimeEnvSetupError as e:
            _conda_failed[key] = str(e)
            raise


def _build_conda_env(exe: str, spec: Dict, dest: str, py: str,
                     ready: str) -> str:
    import yaml

    os.makedirs(conda_env_cache_root(), exist_ok=True)
    tmp = f"{dest}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
    spec_path = f"{tmp}.yml"
    with open(spec_path, "w") as f:
        yaml.safe_dump(spec, f)
    try:
        try:
            proc = subprocess.run(
                [exe, "env", "create", "-p", tmp, "-f", spec_path],
                capture_output=True, text=True, timeout=1800)
        except subprocess.TimeoutExpired as e:
            # deterministic-failure class: without this, the raylet
            # treats the raw TimeoutExpired as transient and re-runs the
            # 30-minute build forever while callers hang
            raise RuntimeEnvSetupError(
                "conda env create timed out after 1800s") from e
        except OSError as e:
            raise RuntimeEnvSetupError(
                f"conda executable failed to run: {e}") from e
        if proc.returncode != 0:
            raise RuntimeEnvSetupError(
                f"conda env create failed (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}")
        if not os.path.exists(os.path.join(tmp, "bin", "python")):
            raise RuntimeEnvSetupError(
                "conda env create produced no python interpreter "
                f"under {tmp}")
        # Inject the running framework into the env (reference conda.py
        # injects ray + its deps the same way): a .pth appending the
        # builder's site dirs AFTER the env's own site-packages, so the
        # env's packages shadow them but ray_tpu stays importable.
        import glob as _glob
        import site as _site

        env_sites = _glob.glob(
            os.path.join(tmp, "lib", "python*", "site-packages"))
        if env_sites:
            repo_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            with open(os.path.join(env_sites[0], "_ray_tpu_base.pth"),
                      "w") as f:
                f.write(repo_root + "\n")
                for p in _site.getsitepackages():
                    f.write(p + "\n")
        with open(os.path.join(tmp, ".ready"), "w"):
            pass
        try:
            os.replace(tmp, dest)  # first builder wins
        except OSError:
            if not os.path.exists(ready):
                raise
        # same LRU cap as the pip venv cache — conda envs are even
        # bigger, and nothing else bounds the cache directory
        _evict_pip_cache(conda_env_cache_root(),
                         keep=os.path.basename(dest))
        return py
    finally:
        try:
            os.unlink(spec_path)
        except OSError:
            pass
        # failure (or a lost rename race) must not leak the
        # multi-hundred-MB partial env; on success tmp no longer exists
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# container stand-in: local overlay images (reference
# python/ray/_private/runtime_env/{container,image_uri}.py runs workers in
# podman; a zero-egress single-box deployment has no registry or container
# runtime, so the shipped plugin applies a LOCAL image directory as a
# userspace overlay — `<image>/site-packages` prepends sys.path,
# `<image>/bin` prepends PATH. The plugin seam accepts a real podman
# backend where one exists.)
# ---------------------------------------------------------------------------


class LocalImagePlugin(RuntimeEnvPlugin):
    name = "container"

    def prepare(self, value, upload) -> Any:
        if not isinstance(value, dict) or "image" not in value:
            raise ValueError(
                'runtime_env container must be {"image": <local overlay '
                'dir>} (zero-egress stand-in for the reference\'s podman '
                "images)")
        unknown = set(value) - {"image"}
        if unknown:
            raise ValueError(
                f"unsupported container fields: {sorted(unknown)}")
        return {"image": str(value["image"])}

    def materialize(self, value, fetch, target_root: str) -> None:
        image = value["image"]
        if not os.path.isdir(image):
            raise RuntimeError(
                f"container image dir {image!r} does not exist on this "
                f"node (images are node-local, like pulled containers)")
        site = os.path.join(image, "site-packages")
        if os.path.isdir(site) and site not in sys.path:
            sys.path.insert(0, site)
        bindir = os.path.join(image, "bin")
        if os.path.isdir(bindir):
            os.environ["PATH"] = (
                bindir + os.pathsep + os.environ.get("PATH", ""))


register_plugin(LocalImagePlugin())
