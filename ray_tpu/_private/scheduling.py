"""Cluster scheduling policies.

Reference: `src/ray/raylet/scheduling/policy/` — hybrid (pack until a
utilization threshold, then spread), spread, node-affinity, and
placement-group bundle policies, all over a cluster resource view synced from
heartbeats (the ray_syncer equivalent). Used by both raylets (task leases)
and the GCS (actor creation, placement-group bundle placement).
"""

from __future__ import annotations

import heapq
import random
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import task as task_mod

# Placement tiebreaks draw from a dedicated stream, never the global
# `random` module: under RAY_TPU_CHAOS the stream comes from the
# FaultPlan's per-site seeded streams so the scheduling decision sequence
# replays identically with the fault schedule; without a plan it is an
# ordinary process-local stream.
_DEFAULT_RNG = random.Random()


class _SchedStats:
    """Process-wide scheduling counters (flight-recorder plane): plain
    integer increments on the decision path, exposed at scrape time via
    `metrics_text()` from the daemons' /metrics extra_text.

    `no_feasible` counts demands NO alive node could ever satisfy
    (total < demand everywhere — the autoscaler must add bigger nodes);
    `no_capacity` counts demands that fit some node's total but nothing
    RIGHT NOW (transiently full — more of the same nodes, or just wait).
    Conflating the two made the autoscaler size for phantom demand.
    """

    __slots__ = ("pick_calls", "no_feasible", "no_capacity",
                 "bundle_placements", "bundle_failures", "job_granted",
                 "job_deferred")

    def __init__(self):
        self.pick_calls = 0
        self.no_feasible = 0
        self.no_capacity = 0
        self.bundle_placements = 0
        self.bundle_failures = 0
        # per-job rows ({job=} labels in /metrics): leases granted in
        # fair-queue order, and dispatches deferred by admission control
        # because the job was over its cpu/memory quota
        self.job_granted: Dict[str, int] = {}
        self.job_deferred: Dict[str, int] = {}


SCHED_STATS = _SchedStats()


def metrics_text() -> str:
    s = SCHED_STATS
    lines = [
        "# TYPE scheduler_pick_node_total counter",
        f"scheduler_pick_node_total {s.pick_calls}",
        "# TYPE scheduler_no_feasible_total counter",
        f"scheduler_no_feasible_total {s.no_feasible}",
        "# TYPE scheduler_no_capacity_total counter",
        f"scheduler_no_capacity_total {s.no_capacity}",
        "# TYPE scheduler_bundle_placements_total counter",
        f"scheduler_bundle_placements_total {s.bundle_placements}",
        f"scheduler_bundle_failures_total {s.bundle_failures}",
    ]
    if s.job_granted:
        lines.append("# TYPE scheduler_job_granted_total counter")
        for job, n in sorted(s.job_granted.items()):
            lines.append(f'scheduler_job_granted_total{{job="{job}"}} {n}')
    if s.job_deferred:
        lines.append("# TYPE scheduler_job_deferred_total counter")
        for job, n in sorted(s.job_deferred.items()):
            lines.append(f'scheduler_job_deferred_total{{job="{job}"}} {n}')
    return "\n".join(lines) + "\n"


def _tiebreak_rng() -> random.Random:
    plan = _fi.plan()
    if plan is not None:
        return plan.rng_for("scheduling.tiebreak")
    return _DEFAULT_RNG


# ---------------------------------------------------------------------------
# per-job quotas + weighted-fair dispatch (multi-tenant isolation plane)
# ---------------------------------------------------------------------------


@dataclass
class JobQuota:
    """Per-job resource limits + fair-share weight, registered at job
    submission (`ray_tpu.init(job_quotas=...)` → GCS `register_job` →
    every raylet via the jobs pubsub channel). Zero means unlimited for
    the quota fields; `weight` sets the job's share of contended
    dispatch (a weight-2 job drains twice as fast as a weight-1 job
    when both are backlogged)."""

    weight: float = 1.0
    cpu: float = 0.0
    memory: float = 0.0
    object_store_bytes: int = 0

    @classmethod
    def from_dict(cls, d: Dict) -> "JobQuota":
        return cls(
            weight=float(d.get("weight", 1.0) or 1.0),
            cpu=float(d.get("cpu", 0.0) or 0.0),
            memory=float(d.get("memory", 0.0) or 0.0),
            object_store_bytes=int(d.get("object_store_bytes", 0) or 0),
        )

    def to_dict(self) -> Dict:
        return {"weight": self.weight, "cpu": self.cpu,
                "memory": self.memory,
                "object_store_bytes": self.object_store_bytes}


_DEFAULT_QUOTA = JobQuota()
JOB_QUOTAS: Dict[bytes, JobQuota] = {}


def set_job_quota(job_id: bytes, quota: JobQuota) -> None:
    JOB_QUOTAS[job_id] = quota


def job_quota(job_id: bytes) -> JobQuota:
    return JOB_QUOTAS.get(job_id, _DEFAULT_QUOTA)


def job_label(job_id: bytes) -> str:
    """Short stable {job=} label for /metrics rows."""
    return job_id.hex()[:8] if job_id else "none"


class FairDispatchQueue:
    """Weighted-fair queue over per-job FIFO lanes.

    Replaces the raylet's FIFO `_pending` list: each job owns a lane,
    and contended dispatch drains lanes deficit-round-robin — every
    grant advances the job's virtual clock by `cost / weight`, and
    `fair_scan()` orders all queued items lowest-clock-first (the
    job with the largest accumulated deficit relative to its weight
    goes first). Long-run grant shares therefore track weights: a
    weight-4 lane drains 4× a weight-1 lane while both are backlogged,
    and within a lane FIFO order is preserved.

    A job (re)entering the queue is floored to the current backlogged
    minimum clock — or, when nothing is backlogged, to the highest
    clock ever charged — so idle time banks no credit in EITHER
    direction: an idle incumbent cannot burst on return, and a
    late-arriving job cannot claim catch-up service for time before it
    existed. Single-threaded like the raylet event loop — no internal
    locking.
    """

    def __init__(self, cost_of: Optional[Callable] = None,
                 weight_of: Optional[Callable] = None):
        self._lanes: Dict[bytes, deque] = {}
        self._vtime: Dict[bytes, float] = {}
        self._vmax = 0.0  # highest clock ever charged (idle-entry floor)
        self._cost_of = cost_of or (lambda item: 1.0)
        self._weight_of = weight_of or (
            lambda job: job_quota(job).weight)

    # -- list-compatible surface (the raylet's _pending call sites) ----

    def __len__(self) -> int:
        return sum(len(lane) for lane in self._lanes.values())

    def __bool__(self) -> bool:
        return any(self._lanes.values())

    def __iter__(self) -> Iterable:
        return iter(self.fair_scan())

    def __contains__(self, item) -> bool:
        return any(any(it is item for it in lane)
                   for lane in self._lanes.values())

    def push(self, job: bytes, item) -> None:
        lane = self._lanes.get(job)
        if lane is None:
            lane = self._lanes[job] = deque()
        if not lane:
            # joining the backlog: start at the backlogged frontier, or
            # at the global high-water clock when the queue is idle (a
            # brand-new job must not out-deficit an incumbent that
            # already drained its work)
            active = [self._vtime.get(j, 0.0)
                      for j, l in self._lanes.items() if l and j != job]
            floor = min(active) if active else self._vmax
            self._vtime[job] = max(self._vtime.get(job, 0.0), floor)
        lane.append(item)

    def remove(self, item) -> bool:
        """Remove by identity (leases are mutable dataclasses — equality
        would be both slow and wrong here)."""
        for job, lane in self._lanes.items():
            for i, it in enumerate(lane):
                if it is item:
                    del lane[i]
                    if not lane:
                        del self._lanes[job]
                    return True
        return False

    # -- fair order ----------------------------------------------------

    def fair_scan(self) -> List:
        """Every queued item in weighted-fair order. Pure simulation:
        the real per-job clocks only advance on `charge()` (an actual
        grant), so skipped items (deps not ready, node full) cost their
        job nothing."""
        heap = []
        pos: Dict[bytes, int] = {}
        for k, (job, lane) in enumerate(self._lanes.items()):
            if lane:
                heapq.heappush(heap, (self._vtime.get(job, 0.0), k, job))
                pos[job] = 0
        out: List = []
        while heap:
            v, k, job = heapq.heappop(heap)
            lane = self._lanes[job]
            item = lane[pos[job]]
            out.append(item)
            pos[job] += 1
            v += self._cost_of(item) / max(self._weight_of(job), 1e-9)
            if pos[job] < len(lane):
                heapq.heappush(heap, (v, k, job))
        return out

    def head(self, n: int) -> List:
        """First n items in fair order (heartbeat demand reporting)."""
        return self.fair_scan()[:n]

    def charge(self, job: bytes, item) -> None:
        """Commit a grant: advance the job's virtual clock and its
        {job=} grant counter."""
        w = max(self._weight_of(job), 1e-9)
        v = self._vtime.get(job, 0.0) + self._cost_of(item) / w
        self._vtime[job] = v
        if v > self._vmax:
            self._vmax = v
        label = job_label(job)
        SCHED_STATS.job_granted[label] = \
            SCHED_STATS.job_granted.get(label, 0) + 1

    def depths(self) -> Dict[str, int]:
        """Queue depth per job label (scheduler_queue_depth{job=})."""
        return {job_label(job): len(lane)
                for job, lane in self._lanes.items() if lane}


@dataclass
class NodeResources:
    node_id: bytes
    raylet_addr: str
    total: Dict[str, float] = field(default_factory=dict)
    available: Dict[str, float] = field(default_factory=dict)
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) >= v for k, v in demand.items() if v > 0)

    def fits_now(self, demand: Dict[str, float]) -> bool:
        return all(
            self.available.get(k, 0.0) >= v for k, v in demand.items() if v > 0
        )

    def utilization(self) -> float:
        parts = []
        for key, total in self.total.items():
            if total > 0:
                parts.append(1.0 - self.available.get(key, 0.0) / total)
        return max(parts) if parts else 0.0


class ClusterView:
    """A consistent snapshot of per-node resources, updated from heartbeats."""

    def __init__(self):
        self.nodes: Dict[bytes, NodeResources] = {}

    def update_node(self, node_id: bytes, raylet_addr: str,
                    total: Dict[str, float], available: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None):
        node = self.nodes.get(node_id)
        if node is None:
            self.nodes[node_id] = NodeResources(
                node_id, raylet_addr, dict(total), dict(available),
                labels=dict(labels or {}),
            )
        else:
            node.total = dict(total)
            node.available = dict(available)
            node.raylet_addr = raylet_addr
            if labels is not None:
                node.labels = dict(labels)

    def remove_node(self, node_id: bytes):
        self.nodes.pop(node_id, None)

    def alive_nodes(self) -> List[NodeResources]:
        return [n for n in self.nodes.values() if n.alive]


def pick_node(
    view: ClusterView,
    spec_resources: Dict[str, float],
    strategy: str = task_mod.STRATEGY_DEFAULT,
    local_node_id: Optional[bytes] = None,
    target_node_id: Optional[bytes] = None,
    soft: bool = False,
    spread_threshold: float = 0.5,
    rng: random.Random | None = None,
) -> Optional[NodeResources]:
    """Select a node for a task/actor. Returns None if nothing is feasible
    right now (caller queues and retries when resources free up)."""
    SCHED_STATS.pick_calls += 1
    node = _pick_node_impl(view, spec_resources, strategy, local_node_id,
                           target_node_id, soft, spread_threshold, rng)
    if node is None:
        # Split the failure signal the autoscaler sizes from: a demand
        # some alive node could EVENTUALLY satisfy (total fits, just
        # busy now) is lack of capacity; a demand no node's total can
        # ever hold (or an empty cluster) is genuinely infeasible.
        if any(n.feasible(spec_resources) for n in view.alive_nodes()):
            SCHED_STATS.no_capacity += 1
        else:
            SCHED_STATS.no_feasible += 1
    return node


def _pick_node_impl(
    view: ClusterView,
    spec_resources: Dict[str, float],
    strategy: str,
    local_node_id: Optional[bytes],
    target_node_id: Optional[bytes],
    soft: bool,
    spread_threshold: float,
    rng: random.Random | None,
) -> Optional[NodeResources]:
    nodes = view.alive_nodes()
    if not nodes:
        return None

    if strategy == task_mod.STRATEGY_NODE_AFFINITY and target_node_id is not None:
        for n in nodes:
            if n.node_id == target_node_id:
                if n.fits_now(spec_resources):
                    return n
                return None if not soft else _best_fit(nodes, spec_resources)
        return _best_fit(nodes, spec_resources) if soft else None

    if strategy == task_mod.STRATEGY_SPREAD:
        fitting = [n for n in nodes if n.fits_now(spec_resources)]
        if not fitting:
            return None
        # Least-utilized first; random tiebreak for even spread.
        (rng or _tiebreak_rng()).shuffle(fitting)
        return min(fitting, key=lambda n: n.utilization())

    # DEFAULT hybrid policy: prefer the local node while it is under the
    # spread threshold, else pick the best (lowest-utilization) fitting node.
    local = None
    if local_node_id is not None:
        for n in nodes:
            if n.node_id == local_node_id:
                local = n
                break
    if (
        local is not None
        and local.fits_now(spec_resources)
        and local.utilization() <= spread_threshold
    ):
        return local
    return _best_fit(nodes, spec_resources, rng)


def _best_fit(nodes: List[NodeResources], demand: Dict[str, float],
              rng: random.Random | None = None):
    fitting = [n for n in nodes if n.fits_now(demand)]
    if not fitting:
        return None
    # Random tiebreak: min() on equal utilizations is stable, which
    # would pile every weightless placement (actors release their CPU
    # after creation, so utilization never rises between heartbeats)
    # onto whichever node happens to list first.
    (rng or _tiebreak_rng()).shuffle(fitting)
    return min(fitting, key=lambda n: n.utilization())


def place_bundles(
    view: ClusterView,
    bundles: List[Dict[str, float]],
    strategy: str,
) -> Optional[List[NodeResources]]:
    """Choose a node per bundle (reference: bundle_scheduling_policy.cc).

    PACK: minimize node count (best effort). STRICT_PACK: all on one node.
    SPREAD: prefer distinct nodes (best effort). STRICT_SPREAD: distinct
    nodes required. Returns None if infeasible (all-or-nothing).
    """
    placement = _place_bundles_impl(view, bundles, strategy)
    if placement is None:
        SCHED_STATS.bundle_failures += 1
    else:
        SCHED_STATS.bundle_placements += 1
    return placement


def _place_bundles_impl(
    view: ClusterView,
    bundles: List[Dict[str, float]],
    strategy: str,
) -> Optional[List[NodeResources]]:
    nodes = view.alive_nodes()
    remaining = {
        n.node_id: dict(n.available) for n in nodes
    }
    by_id = {n.node_id: n for n in nodes}

    def try_place(node_id: bytes, demand: Dict[str, float]) -> bool:
        avail = remaining[node_id]
        if all(avail.get(k, 0.0) >= v for k, v in demand.items() if v > 0):
            for k, v in demand.items():
                avail[k] = avail.get(k, 0.0) - v
            return True
        return False

    placement: List[NodeResources] = []

    if strategy in ("PACK", "STRICT_PACK"):
        order = sorted(nodes, key=lambda n: n.utilization())
        for demand in bundles:
            placed = False
            # Prefer nodes already used by earlier bundles.
            used_ids = [n.node_id for n in placement]
            candidates = used_ids + [
                n.node_id for n in order if n.node_id not in used_ids
            ]
            for node_id in candidates:
                if try_place(node_id, demand):
                    placement.append(by_id[node_id])
                    placed = True
                    break
            if not placed:
                return None
        if strategy == "STRICT_PACK" and len({n.node_id for n in placement}) > 1:
            return None
        return placement

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        for demand in bundles:
            used_ids = {n.node_id for n in placement}
            fresh = [n for n in nodes if n.node_id not in used_ids]
            candidates = sorted(fresh, key=lambda n: n.utilization())
            if strategy == "SPREAD":
                candidates += sorted(
                    [n for n in nodes if n.node_id in used_ids],
                    key=lambda n: n.utilization(),
                )
            placed = False
            for node in candidates:
                if try_place(node.node_id, demand):
                    placement.append(node)
                    placed = True
                    break
            if not placed:
                return None
        return placement

    raise ValueError(f"unknown placement strategy: {strategy}")


def place_slice_bundles(
    view: ClusterView,
    bundles: List[Dict[str, float]],
    topology: str,
) -> Optional[List[NodeResources]]:
    """Atomically place one bundle per host of ONE TPU pod slice.

    TPU-first extension of bundle_scheduling_policy: a slice is the set of
    raylets sharing `ray_tpu.slice_name` with `ray_tpu.slice_type ==
    topology`. A slice is eligible only when ALL of its hosts are alive
    and registered (ICI is slice-internal — a partial slice cannot form
    the mesh), the bundle count equals the host count, and every host fits
    its bundle. Bundle i lands on slice host i, so `jax.distributed`
    process_id == bundle_index matches ICI topology order. All-or-nothing:
    returns None (caller keeps the PG pending) when no complete slice
    fits.
    """
    from ray_tpu._private import accelerators as acc

    slices: Dict[str, List[NodeResources]] = {}
    for n in view.alive_nodes():
        if n.labels.get(acc.LABEL_SLICE_TYPE) != topology:
            continue
        name = n.labels.get(acc.LABEL_SLICE_NAME)
        if name is None:
            continue  # malformed registration — never poison scheduling
        slices.setdefault(name, []).append(n)

    candidates = []
    for name, hosts in slices.items():
        try:
            declared = int(
                hosts[0].labels.get(acc.LABEL_SLICE_NUM_HOSTS, "1"))
            by_host_id = sorted(
                hosts,
                key=lambda n: int(n.labels.get(acc.LABEL_SLICE_HOST_ID,
                                               "-1")))
            ids = [int(n.labels.get(acc.LABEL_SLICE_HOST_ID, "-1"))
                   for n in by_host_id]
        except ValueError:
            continue  # non-integer label values — skip the slice
        if len(hosts) != declared or len(bundles) != declared:
            continue
        if ids != list(range(declared)):
            continue  # duplicate/missing host ids — not a coherent slice
        if all(node.fits_now(demand)
               for node, demand in zip(by_host_id, bundles)):
            candidates.append(by_host_id)

    if not candidates:
        return None
    # least-loaded slice first (keep busy slices free for their tenants)
    return min(candidates,
               key=lambda hosts: max(n.utilization() for n in hosts))
