"""Asyncio RPC: length-prefixed msgpack frames over TCP.

This is the control-plane transport equivalent of the reference's gRPC layer
(`src/ray/rpc/`): every daemon (GCS, raylet, worker) runs an `RpcServer` with
named async handlers, and holds `RpcClient` connections to its peers. Direct
worker→worker task push (the reference's `CoreWorkerService.PushTask`) rides
the same transport. Payloads are msgpack maps; binary blobs (pickled task
args, serialized objects) are msgpack `bytes` and are never copied through
JSON/base64.

Frame format:  u32_be length | msgpack [msgid, kind, method, payload]
kinds: 0=request 1=reply_ok 2=reply_err 3=notify
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import traceback
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ray_tpu._private import fault_injection as _fi

logger = logging.getLogger(__name__)

REQUEST, REPLY_OK, REPLY_ERR, NOTIFY = 0, 1, 2, 3

_MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    pass


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return len(body).to_bytes(4, "big") + body


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > _MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


class RpcServer:
    """Serves named async handlers. Handlers: async def h(payload) -> payload."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable[[Any], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    def register(self, method: str, handler: Callable[[Any], Awaitable[Any]]):
        self._handlers[method] = handler

    def register_all(self, obj, prefix: str = "rpc_"):
        """Register every `rpc_*` coroutine method of obj under its bare name."""
        for name in dir(obj):
            if name.startswith(prefix):
                self.register(name[len(prefix):], getattr(obj, name))

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self):
        if self._server:
            self._server.close()
        # Cancel live connection handlers BEFORE wait_closed(): on
        # Python >= 3.12.1 wait_closed() waits for all handlers, which would
        # otherwise block forever on connections idling in _read_frame.
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server:
            await self._server.wait_closed()

    async def _handle_conn(self, reader, writer):
        write_lock = asyncio.Lock()
        conn_task = asyncio.current_task()
        self._conn_tasks.add(conn_task)
        try:
            while True:
                try:
                    msgid, kind, method, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                task = asyncio.ensure_future(
                    self._dispatch(msgid, kind, method, payload, writer, write_lock)
                )
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
        except asyncio.CancelledError:
            pass
        finally:
            self._conn_tasks.discard(conn_task)
            writer.close()

    async def _dispatch(self, msgid, kind, method, payload, writer, write_lock):
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            result = await handler(payload)
            reply = [msgid, REPLY_OK, method, result]
        except asyncio.CancelledError:
            raise
        except BaseException:
            if kind == NOTIFY:
                logger.exception("error in notify handler %s", method)
                return
            reply = [msgid, REPLY_ERR, method, traceback.format_exc()]
        if kind == REQUEST:
            try:
                async with write_lock:
                    writer.write(_pack(reply))
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                pass


class RpcClient:
    """Persistent connection to one RpcServer; safe for concurrent requests."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._timeout = connect_timeout
        self._reader = None
        self._writer = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._msgid = itertools.count(1)
        self._read_task = None
        self._lock = asyncio.Lock()
        self._closed = False
        self._dead = False  # read loop saw EOF/reset — no replies can come

    async def connect(self):
        deadline = asyncio.get_event_loop().time() + self._timeout
        while True:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self._host, self._port
                )
                break
            except OSError:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.05)
        self._read_task = asyncio.ensure_future(self._read_loop())
        return self

    async def _read_loop(self):
        try:
            while True:
                msgid, kind, method, payload = await _read_frame(self._reader)
                if _fi._PLAN is not None:
                    act = _fi._PLAN.rpc_recv(method)
                    if act is not None:
                        if act[1]:
                            await asyncio.sleep(act[1])  # delayed delivery
                        if act[0]:
                            continue  # reply lost on the wire
                fut = self._pending.pop(msgid, None)
                if fut is None or fut.done():
                    continue
                if kind == REPLY_OK:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RpcError(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError,
                asyncio.CancelledError):
            # the peer is gone: no reply will EVER arrive on this
            # connection — mark dead so `connected` stops advertising it
            # (a not-yet-closing writer would otherwise let new calls
            # wait forever on a drained pending table)
            self._dead = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(self.address))
            self._pending.clear()

    async def call(self, method: str, payload: Any = None,
                   timeout: float | None = None) -> Any:
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        msgid = next(self._msgid)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msgid] = fut
        frame = _pack([msgid, REQUEST, method, payload])
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if delay:
                    await asyncio.sleep(delay)
                if drop:
                    frame = b""  # request lost: the pending future only
                    # resolves via the caller's timeout / retry machinery
                elif dup:
                    frame = frame + frame  # at-least-once duplication;
                    # the second reply's msgid is already popped, ignored
        if frame:
            async with self._lock:
                self._writer.write(frame)
                await self._writer.drain()
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def call_nowait(self, method: str, payload: Any = None) -> asyncio.Future:
        """Loop-thread-only fast path: write the request frame synchronously
        (StreamWriter.write appends a whole frame atomically, so no lock and
        no drain round-trip) and return the pending reply future."""
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        msgid = next(self._msgid)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msgid] = fut
        frame = _pack([msgid, REQUEST, method, payload])
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if drop:
                    return fut  # lost: resolves via caller timeout/retry
                if dup:
                    frame = frame + frame
                if delay:
                    # sync fast path cannot await: reschedule the write
                    def _late_write(w=self._writer, f=frame):
                        if not w.is_closing():
                            w.write(f)
                    asyncio.get_event_loop().call_later(delay, _late_write)
                    return fut
        self._writer.write(frame)
        return fut

    async def notify(self, method: str, payload: Any = None):
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        frame = _pack([0, NOTIFY, method, payload])
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if delay:
                    await asyncio.sleep(delay)
                if drop:
                    return  # fire-and-forget frame lost entirely
                if dup:
                    frame = frame + frame
        async with self._lock:
            self._writer.write(frame)
            await self._writer.drain()

    async def close(self):
        self._closed = True
        if self._read_task:
            self._read_task.cancel()
        if self._writer:
            self._writer.close()

    @property
    def connected(self) -> bool:
        return (self._writer is not None
                and not self._writer.is_closing()
                and not self._dead)


class ReconnectingClient:
    """A stable handle to a peer that may restart (the GCS): every call
    resolves the live connection through the pool and retries once after
    re-establishing it (reference: the gRPC channel's transparent
    reconnect that raylet/worker GCS clients rely on)."""

    def __init__(self, pool: "ClientPool", address: str):
        self._pool = pool
        self._address = address

    @property
    def address(self) -> str:
        return self._address

    async def call(self, method: str, payload, timeout: float = 30.0):
        for attempt in (0, 1):
            client = await self._pool.get(self._address)
            if client._dead:
                # stale pool entry: refresh and retry the CONNECT — the
                # request was never sent, so this is always safe
                self._pool.invalidate(self._address)
                if attempt:
                    raise ConnectionLost(self._address)
                await asyncio.sleep(0.2)
                continue
            try:
                return await client.call(method, payload, timeout=timeout)
            except ConnectionLost:
                # the request MAY have been applied before the peer went
                # away — blindly replaying would double-apply mutations
                # (e.g. a named-actor registration). Invalidate so the
                # next call reconnects, and surface the loss.
                self._pool.invalidate(self._address)
                raise

    async def notify(self, method: str, payload):
        client = await self._pool.get(self._address)
        try:
            await client.notify(method, payload)
        except ConnectionLost:
            self._pool.invalidate(self._address)
            raise


class ClientPool:
    """Lazily-created, cached RpcClients keyed by address (reference:
    per-service client pools in `src/ray/rpc/`)."""

    def __init__(self):
        self._clients: Dict[str, RpcClient] = {}
        self._locks: Dict[str, asyncio.Lock] = {}

    def get_cached(self, address: str) -> Optional[RpcClient]:
        """Synchronous lookup; None when no live connection exists yet."""
        client = self._clients.get(address)
        if client is not None and client.connected:
            return client
        return None

    async def get(self, address: str) -> RpcClient:
        client = self.get_cached(address)
        if client is not None:
            return client
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            client = self.get_cached(address)
            if client is not None:
                return client
            client = RpcClient(address)
            await client.connect()
            self._clients[address] = client
            return client

    def invalidate(self, address: str):
        client = self._clients.pop(address, None)
        if client:
            asyncio.ensure_future(client.close())

    async def close_all(self):
        for client in self._clients.values():
            await client.close()
        self._clients.clear()
