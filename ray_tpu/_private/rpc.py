"""Asyncio RPC: length-prefixed msgpack frames over TCP.

This is the control-plane transport equivalent of the reference's gRPC layer
(`src/ray/rpc/`): every daemon (GCS, raylet, worker) runs an `RpcServer` with
named async handlers, and holds `RpcClient` connections to its peers. Direct
worker→worker task push (the reference's `CoreWorkerService.PushTask`) rides
the same transport. Payloads are msgpack maps; binary blobs (pickled task
args, serialized objects) are msgpack `bytes` and are never copied through
JSON/base64.

Frame format:  u32_be length | msgpack [msgid, kind, method, payload]
kinds: 0=request 1=reply_ok 2=reply_err 3=notify 4=batch

A BATCH frame carries N logical messages in one wire frame: its payload is a
list of individually msgpack-packed `[msgid, kind, method, payload]` bodies.
Both sides run a per-connection write coalescer (`_WriteCoalescer`): the
first message on a cold connection writes through immediately (serial
request/response traffic pays no added latency) and opens a one-tick
window; every message queued within that same event-loop tick — plus a
size/count watermark — folds into one BATCH frame: one `_pack`, one
syscall, one drain for N logical messages (reference: gRPC's stream write
coalescing in `src/ray/rpc/`). A single queued message is emitted as a
plain frame, byte-identical to the unbatched format. Fault injection (`rpc_send` /
`rpc_recv`) acts per *logical* message, never per wire frame, so seeded
FaultPlan replays stay valid with batching on.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
import traceback
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private import fault_injection as _fi

logger = logging.getLogger(__name__)

REQUEST, REPLY_OK, REPLY_ERR, NOTIFY, BATCH = 0, 1, 2, 3, 4

_MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Remote handler raised; carries the remote traceback string."""


class ConnectionLost(Exception):
    pass


def _pack(msg) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return len(body).to_bytes(4, "big") + body


async def _read_frame(reader: asyncio.StreamReader):
    header = await reader.readexactly(4)
    length = int.from_bytes(header, "big")
    if length > _MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False, strict_map_key=False)


def _unbatch(bodies: List[bytes]):
    RPC_STATS.batch_frames_recv += 1
    RPC_STATS.messages_unbatched += len(bodies)
    for body in bodies:
        yield msgpack.unpackb(body, raw=False, strict_map_key=False)


class _RpcStats:
    """Process-wide frame-coalescing counters (every connection feeds the
    same instance; per-connection figures live on each `_WriteCoalescer`).
    `messages_sent / frames_sent` is the amortization factor the batching
    win comes from — scraped through `/metrics` on every daemon and read
    directly by `bench.py` for attribution."""

    __slots__ = ("messages_sent", "frames_sent", "batches_sent",
                 "messages_batched", "drain_backoffs", "batch_frames_recv",
                 "messages_unbatched")

    def __init__(self):
        self.reset()

    def reset(self):
        for name in self.__slots__:
            setattr(self, name, 0)

    def as_dict(self) -> Dict[str, int]:
        return {name: getattr(self, name) for name in self.__slots__}


RPC_STATS = _RpcStats()


def metrics_text() -> str:
    s = RPC_STATS
    lines = ["# TYPE rpc_coalescing counter"]
    lines += [f"rpc_{name} {getattr(s, name)}" for name in _RpcStats.__slots__]
    return "\n".join(lines) + "\n"


try:  # join every daemon's /metrics scrape (like the channel frame plane)
    from ray_tpu.util import metrics as _metrics

    _metrics.DEFAULT_REGISTRY.register_callback("rpc_coalescing", metrics_text)
except Exception:  # noqa: BLE001 — metrics are never load-bearing
    pass


def _batch_knobs():
    from ray_tpu._private.config import global_config

    cfg = global_config()
    return (max(1, cfg.rpc_batch_max_msgs), cfg.rpc_batch_max_bytes,
            cfg.rpc_send_high_watermark)


class _WriteCoalescer:
    """Per-connection write-side coalescer. Loop-thread only.

    Write-through first: on a cold connection (nothing queued, no open
    tick window) the message is written immediately as a plain frame —
    zero added latency for serial request/response traffic — and a
    one-tick window opens; every message sent within that same
    event-loop tick queues behind it and flushes as one BATCH frame on
    the next tick (`call_soon`). Crossing the count or byte watermark
    flushes immediately. The flush itself never runs under a lock — the
    timer-started flush pattern from PR-2's pubsub batching fix. When
    the transport buffer crosses the high-watermark the coalescer stops
    writing and parks behind one awaited `drain()` (backpressure: a
    slow peer queues messages here instead of growing the kernel send
    buffer unboundedly); awaited senders can additionally park in
    `send_wait()` until the drain clears."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._loop = asyncio.get_event_loop()
        self._max_msgs, self._max_bytes, self._high_watermark = _batch_knobs()
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self._scheduled = False
        self._tick_open = False
        self._draining = False
        self._drain_waiters: List[asyncio.Future] = []
        # per-connection coalescing counters (aggregate lives in RPC_STATS)
        self.messages_sent = 0
        self.frames_sent = 0
        self.batches_sent = 0

    def send(self, msg) -> None:
        """Queue one logical `[msgid, kind, method, payload]` message."""
        body = msgpack.packb(msg, use_bin_type=True)
        self.messages_sent += 1
        RPC_STATS.messages_sent += 1
        if (not self._pending and not self._tick_open and not self._draining
                and not self._writer.is_closing()):
            # cold connection: write through — serial round trips pay no
            # coalescing latency; same-tick followers batch behind this
            self._writer.write(len(body).to_bytes(4, "big") + body)
            self.frames_sent += 1
            RPC_STATS.frames_sent += 1
            self._tick_open = True
            self._loop.call_soon(self._close_tick)
            self._check_watermark()
            return
        self._pending.append(body)
        self._pending_bytes += len(body)
        if (len(self._pending) >= self._max_msgs
                or self._pending_bytes >= self._max_bytes):
            self._flush()
        elif not self._scheduled:
            self._scheduled = True
            self._loop.call_soon(self._tick_flush)

    def _close_tick(self):
        self._tick_open = False

    def _tick_flush(self):
        self._scheduled = False
        self._flush()

    def _flush(self):
        if not self._pending or self._draining:
            return  # draining: the drain task re-flushes when it clears
        if self._writer.is_closing():
            self._pending.clear()
            self._pending_bytes = 0
            return
        bodies, self._pending = self._pending, []
        self._pending_bytes = 0
        if len(bodies) == 1:
            body = bodies[0]  # plain frame — byte-identical to unbatched
            self._writer.write(len(body).to_bytes(4, "big") + body)
        else:
            self._writer.write(_pack([0, BATCH, "", bodies]))
            self.batches_sent += 1
            RPC_STATS.batches_sent += 1
            RPC_STATS.messages_batched += len(bodies)
        self.frames_sent += 1
        RPC_STATS.frames_sent += 1
        self._check_watermark()

    def _check_watermark(self):
        transport = self._writer.transport
        if (transport is not None
                and transport.get_write_buffer_size() > self._high_watermark):
            self._draining = True
            RPC_STATS.drain_backoffs += 1
            asyncio.ensure_future(self._drain_then_flush())

    async def _drain_then_flush(self):
        try:
            await self._writer.drain()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        self._draining = False
        for fut in self._drain_waiters:
            if not fut.done():
                fut.set_result(None)
        self._drain_waiters.clear()
        self._flush()

    async def wait_drained(self) -> None:
        """Park until an in-progress backpressure drain clears."""
        while self._draining:
            fut = self._loop.create_future()
            self._drain_waiters.append(fut)
            await fut

    async def send_wait(self, msg) -> None:
        """Awaited variant: when the connection is parked behind a drain,
        wait for it to clear before queueing (backpressure for `call` /
        `notify` / server replies)."""
        if self._draining:
            await self.wait_drained()
        self.send(msg)

    def flush_now(self) -> None:
        """Best-effort synchronous flush (connection teardown)."""
        self._draining = False
        self._flush()


class RpcServer:
    """Serves named async handlers. Handlers: async def h(payload) -> payload."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable[[Any], Awaitable[Any]]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()

    def register(self, method: str, handler: Callable[[Any], Awaitable[Any]]):
        self._handlers[method] = handler

    def register_all(self, obj, prefix: str = "rpc_"):
        """Register every `rpc_*` coroutine method of obj under its bare name."""
        for name in dir(obj):
            if name.startswith(prefix):
                self.register(name[len(prefix):], getattr(obj, name))

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self):
        if self._server:
            self._server.close()
        # Cancel live connection handlers BEFORE wait_closed(): on
        # Python >= 3.12.1 wait_closed() waits for all handlers, which would
        # otherwise block forever on connections idling in _read_frame.
        for t in list(self._conn_tasks):
            t.cancel()
        if self._server:
            await self._server.wait_closed()

    async def _handle_conn(self, reader, writer):
        coal = _WriteCoalescer(writer)
        conn_task = asyncio.current_task()
        self._conn_tasks.add(conn_task)
        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                msgs = _unbatch(msg[3]) if msg[1] == BATCH else (msg,)
                for msgid, kind, method, payload in msgs:
                    task = asyncio.ensure_future(
                        self._dispatch(msgid, kind, method, payload, coal)
                    )
                    self._conn_tasks.add(task)
                    task.add_done_callback(self._conn_tasks.discard)
        except asyncio.CancelledError:
            pass
        finally:
            self._conn_tasks.discard(conn_task)
            coal.flush_now()
            writer.close()

    async def _dispatch(self, msgid, kind, method, payload, coal):
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            result = await handler(payload)
            reply = [msgid, REPLY_OK, method, result]
        except asyncio.CancelledError:
            raise
        except BaseException:
            if kind == NOTIFY:
                logger.exception("error in notify handler %s", method)
                return
            reply = [msgid, REPLY_ERR, method, traceback.format_exc()]
        if kind == REQUEST:
            try:
                # replies completing in the same tick re-batch into one
                # frame; only the backpressured path pays an await
                if coal._draining:
                    await coal.wait_drained()
                coal.send(reply)
            except (ConnectionResetError, BrokenPipeError):
                pass


class RpcClient:
    """Persistent connection to one RpcServer; safe for concurrent requests."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._timeout = connect_timeout
        self._reader = None
        self._writer = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._msgid = itertools.count(1)
        self._read_task = None
        self._coal: Optional[_WriteCoalescer] = None
        self._closed = False
        self._dead = False  # read loop saw EOF/reset — no replies can come

    async def connect(self):
        deadline = asyncio.get_event_loop().time() + self._timeout
        while True:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self._host, self._port
                )
                break
            except OSError:
                if asyncio.get_event_loop().time() > deadline:
                    raise
                await asyncio.sleep(0.05)
        self._coal = _WriteCoalescer(self._writer)
        self._read_task = asyncio.ensure_future(self._read_loop())
        return self

    def _resolve(self, msgid, kind, payload) -> None:
        fut = self._pending.pop(msgid, None)
        if fut is None or fut.done():
            return
        if kind == REPLY_OK:
            fut.set_result(payload)
        else:
            fut.set_exception(RpcError(payload))

    async def _deliver(self, msgid, kind, method, payload):
        # recv faults act per logical reply, even inside a BATCH frame
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_recv(method)
            if act is not None:
                if act[1]:
                    await asyncio.sleep(act[1])  # delayed delivery
                if act[0]:
                    return  # reply lost on the wire
        self._resolve(msgid, kind, payload)

    async def _read_loop(self):
        try:
            while True:
                msg = await _read_frame(self._reader)
                # fault-free fast path resolves inline — one coroutine
                # per reply is measurable at control-plane rates
                if msg[1] == BATCH:
                    for m in _unbatch(msg[3]):
                        if _fi._PLAN is not None:
                            await self._deliver(*m)
                        else:
                            self._resolve(m[0], m[1], m[3])
                elif _fi._PLAN is not None:
                    await self._deliver(*msg)
                else:
                    self._resolve(msg[0], msg[1], msg[3])
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError,
                asyncio.CancelledError):
            # the peer is gone: no reply will EVER arrive on this
            # connection — mark dead so `connected` stops advertising it
            # (a not-yet-closing writer would otherwise let new calls
            # wait forever on a drained pending table)
            self._dead = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(self.address))
            self._pending.clear()

    async def call(self, method: str, payload: Any = None,
                   timeout: float | None = None) -> Any:
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        msgid = next(self._msgid)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msgid] = fut
        msg = [msgid, REQUEST, method, payload]
        dup = False
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if delay:
                    await asyncio.sleep(delay)
                if drop:
                    # request lost: the pending future only resolves via
                    # the caller's timeout / retry machinery
                    if timeout is None:
                        return await fut
                    return await asyncio.wait_for(fut, timeout)
        coal = self._coal
        if coal._draining:
            await coal.wait_drained()
        coal.send(msg)
        if dup:
            # at-least-once duplication; the second reply's msgid is
            # already popped, ignored
            coal.send(msg)
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def call_nowait(self, method: str, payload: Any = None) -> asyncio.Future:
        """Loop-thread-only fast path: queue the request on the write
        coalescer synchronously (no drain round-trip; the coalescer's
        transport high-watermark supplies backpressure) and return the
        pending reply future."""
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        msgid = next(self._msgid)
        fut = asyncio.get_event_loop().create_future()
        self._pending[msgid] = fut
        msg = [msgid, REQUEST, method, payload]
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if drop:
                    return fut  # lost: resolves via caller timeout/retry
                if delay:
                    # sync fast path cannot await: reschedule the queueing
                    def _late_send(c=self._coal, m=msg, d=dup):
                        if not c._writer.is_closing():
                            c.send(m)
                            if d:
                                c.send(m)
                    asyncio.get_event_loop().call_later(delay, _late_send)
                    return fut
                if dup:
                    self._coal.send(msg)
        self._coal.send(msg)
        return fut

    async def notify(self, method: str, payload: Any = None):
        if self._writer is None or self._dead:
            raise ConnectionLost(f"not connected: {self.address}")
        msg = [0, NOTIFY, method, payload]
        dup = False
        if _fi._PLAN is not None:
            act = _fi._PLAN.rpc_send(method)
            if act is not None:
                drop, dup, delay = act
                if delay:
                    await asyncio.sleep(delay)
                if drop:
                    return  # fire-and-forget message lost entirely
        coal = self._coal
        if coal._draining:
            await coal.wait_drained()
        coal.send(msg)
        if dup:
            coal.send(msg)

    async def close(self):
        self._closed = True
        if self._read_task:
            self._read_task.cancel()
        if self._coal:
            self._coal.flush_now()
        if self._writer:
            self._writer.close()

    @property
    def connected(self) -> bool:
        return (self._writer is not None
                and not self._writer.is_closing()
                and not self._dead)


class ReconnectingClient:
    """A stable handle to a peer that may restart (the GCS): every call
    resolves the live connection through the pool and retries once after
    re-establishing it (reference: the gRPC channel's transparent
    reconnect that raylet/worker GCS clients rely on)."""

    def __init__(self, pool: "ClientPool", address: str):
        self._pool = pool
        self._address = address

    @property
    def address(self) -> str:
        return self._address

    async def call(self, method: str, payload, timeout: float = 30.0):
        for attempt in (0, 1):
            client = await self._pool.get(self._address)
            if client._dead:
                # stale pool entry: refresh and retry the CONNECT — the
                # request was never sent, so this is always safe
                self._pool.invalidate(self._address)
                if attempt:
                    raise ConnectionLost(self._address)
                await asyncio.sleep(0.2)
                continue
            try:
                return await client.call(method, payload, timeout=timeout)
            except ConnectionLost:
                # the request MAY have been applied before the peer went
                # away — blindly replaying would double-apply mutations
                # (e.g. a named-actor registration). Invalidate so the
                # next call reconnects, and surface the loss.
                self._pool.invalidate(self._address)
                raise

    async def notify(self, method: str, payload):
        client = await self._pool.get(self._address)
        try:
            await client.notify(method, payload)
        except ConnectionLost:
            self._pool.invalidate(self._address)
            raise


class ClientPool:
    """Lazily-created, cached RpcClients keyed by address (reference:
    per-service client pools in `src/ray/rpc/`)."""

    # a failed connect poisons the address briefly: callers queued
    # behind it — e.g. a raylet draining pulls whose advertised
    # location just died — fail fast instead of each serializing a
    # full connect timeout against the same dead peer
    CONNECT_FAIL_TTL_S = 3.0
    # a GCS death notice poisons for much longer: the control plane
    # already decided the peer is gone, so even the FIRST dial (a full
    # rpc_connect_timeout_s against a black hole) is wasted work. Kept
    # finite so a pathological address reuse self-heals.
    DEAD_ADDR_TTL_S = 60.0

    def __init__(self):
        self._clients: Dict[str, RpcClient] = {}
        self._locks: Dict[str, asyncio.Lock] = {}
        # addr -> (poisoned_at, ttl)
        self._connect_failed_at: Dict[str, Tuple[float, float]] = {}

    def get_cached(self, address: str) -> Optional[RpcClient]:
        """Synchronous lookup; None when no live connection exists yet."""
        client = self._clients.get(address)
        if client is not None and client.connected:
            return client
        return None

    def mark_dead(self, address: str):
        """Record an authoritative death notice (GCS node-removal):
        dials within DEAD_ADDR_TTL_S fail fast with ConnectionLost
        instead of timing out against a peer that no longer exists."""
        self._connect_failed_at[address] = (
            time.monotonic(), self.DEAD_ADDR_TTL_S)

    def _check_poisoned(self, address: str):
        entry = self._connect_failed_at.get(address)
        if entry is None:
            return
        t, ttl = entry
        age = time.monotonic() - t
        if age < ttl:
            raise ConnectionLost(
                f"connect to {address} failed {age:.1f}s ago "
                f"(fail-fast for {ttl:.0f}s)")
        self._connect_failed_at.pop(address, None)

    async def get(self, address: str) -> RpcClient:
        client = self.get_cached(address)
        if client is not None:
            return client
        self._check_poisoned(address)
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            client = self.get_cached(address)
            if client is not None:
                return client
            # re-check under the lock: the head of the queue may have
            # just recorded the failure the rest were waiting on
            self._check_poisoned(address)
            client = RpcClient(address)
            try:
                await client.connect()
            except (OSError, asyncio.TimeoutError):
                self._connect_failed_at[address] = (
                    time.monotonic(), self.CONNECT_FAIL_TTL_S)
                raise
            self._connect_failed_at.pop(address, None)
            self._clients[address] = client
            return client

    def invalidate(self, address: str):
        client = self._clients.pop(address, None)
        if client:
            asyncio.ensure_future(client.close())

    async def close_all(self):
        # snapshot first: an invalidate() racing with shutdown would
        # otherwise mutate the dict mid-iteration; drop the per-address
        # connect locks too (the dict grows forever on a churning pool)
        clients, self._clients = list(self._clients.values()), {}
        self._locks.clear()
        self._connect_failed_at.clear()
        for client in clients:
            await client.close()
