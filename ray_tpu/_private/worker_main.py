"""Worker process entrypoint.

Reference: `python/ray/_private/workers/default_worker.py` — spawned by the
raylet's WorkerPool; connects a CoreWorker to its raylet + GCS, registers,
then blocks in the task-execution loop.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet-addr", required=True)
    parser.add_argument("--gcs-addr", required=True)
    parser.add_argument("--store-name", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--job-id", required=True)
    parser.add_argument("--tpu-chips", default="")
    parser.add_argument("--runtime-env", default="")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {os.getpid()}] %(levelname)s %(name)s: %(message)s",
    )

    # Honor the raylet's platform assignment (a worker spawned without
    # TPU chips must not grab the node's chip) even when a site hook
    # pre-imported jax at interpreter start.
    from ray_tpu._private.accelerators import apply_jax_platforms

    apply_jax_platforms(os.environ.get("JAX_PLATFORMS"))

    from ray_tpu._private import fault_injection as _fi
    from ray_tpu._private.core_worker import CoreWorker
    from ray_tpu._private.ids import JobID
    from ray_tpu._private.object_store import ObjectStore

    _fi.set_role("worker")  # arm worker-scoped timed faults
    chips = tuple(int(c) for c in args.tpu_chips.split(",") if c != "")
    store = ObjectStore.attach(args.store_name)
    cw = CoreWorker(
        mode="worker",
        gcs_addr=args.gcs_addr,
        raylet_addr=args.raylet_addr,
        job_id=JobID.from_hex(args.job_id),
        store=store,
        node_id_hex=args.node_id,
        tpu_chips=chips,
    )
    cw.start()

    env_wire = None
    if args.runtime_env:
        import json

        from ray_tpu._private import runtime_env as renv_mod

        env_wire = json.loads(args.runtime_env)
        # download + extract packages, apply cwd/sys.path before any
        # task runs (env_vars were applied by the raylet at spawn)
        renv_mod.materialize(
            cw, env_wire,
            os.path.join(args.session_dir, "runtime_envs"))
        # Running from a cached pip venv: pin it against LRU eviction
        # with THIS worker's pid — the pin dies with the pool, unlike a
        # raylet-pid marker which would pin every env forever.
        import sys as _sys

        if env_wire.get("pip") and _sys.prefix.startswith(
                renv_mod.pip_env_cache_root()):
            renv_mod.mark_pip_env_in_use(_sys.prefix)
        # introspectable via ray_tpu.get_runtime_context()
        cw.current_runtime_env = env_wire

    async def register():
        from ray_tpu._private import runtime_env as renv_mod

        raylet = await cw._clients.get(args.raylet_addr)
        await raylet.call("register_worker", {
            "worker_id": cw.worker_id.binary(),
            "addr": cw.address,
            "pid": os.getpid(),
            "job_id": cw.job_id.binary(),
            "tpu_chips": list(chips),
            "runtime_env_hash": renv_mod.env_hash(env_wire),
        })

    cw._run_sync(register())

    async def raylet_watchdog():
        # Exit if the raylet disappears (reference: workers die with their
        # raylet via the unix-socket connection; here we poll).
        from ray_tpu._private.rpc import ConnectionLost, RpcError

        while True:
            await asyncio.sleep(2.0)
            try:
                raylet = await cw._clients.get(args.raylet_addr)
                await raylet.call("node_info", {}, timeout=5.0)
            except (ConnectionLost, RpcError, OSError, asyncio.TimeoutError):
                logging.warning("raylet unreachable; worker exiting")
                os._exit(1)

    asyncio.run_coroutine_threadsafe(raylet_watchdog(), cw._loop)
    try:
        cw.run_task_loop()
    except KeyboardInterrupt:
        pass
    finally:
        cw.shutdown()
        sys.exit(0)


if __name__ == "__main__":
    main()
