"""Keyed-shard GCS table (reference: the sharded table storage under
`src/ray/gcs/` that spreads actor/task metadata over Redis shards).

`ShardedTable` is a drop-in `MutableMapping`: callers keep using plain
dict syntax while keys spread over N shards the way `shm_store` sharded
its object index (PR 3) — the point is not in-process lock contention
(the GCS is single-threaded asyncio) but (a) per-shard mutation counters
cheap enough to scrape per `/metrics` hit, exposing *which* slice of the
keyspace is hot, and (b) a stable `shard_index(key)` the GCS reuses to
route write-through persistence onto per-shard writer threads, so
concurrent registrations and event ingestion stop serializing on one
dict + one store thread.

A global insertion sequence is kept per key so recency survives
sharding: `iter_recent()` k-way-merges the shards newest-first (the
task-events table lists most-recent tasks first), and `popitem_oldest()`
evicts the globally oldest entry (the bounded task-events cap).
"""

from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Any, Iterator, List, MutableMapping, Tuple


def shard_index(key: Any, num_shards: int) -> int:
    """Stable key → shard routing (power-of-2 `num_shards`). Bytes keys
    (actor/task IDs are random) route on their first byte; anything else
    on `hash()` — stable within a process, which is all the persist-path
    routing needs (durable records are keyed by name, not by shard)."""
    if isinstance(key, (bytes, bytearray)) and key:
        return key[0] & (num_shards - 1)
    return hash(key) & (num_shards - 1)


class ShardedTable(MutableMapping):
    """Dict-compatible table split over `num_shards` keyed shards."""

    DEFAULT_SHARDS = 8

    def __init__(self, num_shards: int = DEFAULT_SHARDS, name: str = ""):
        if num_shards & (num_shards - 1):
            raise ValueError("num_shards must be a power of 2")
        self.name = name
        self.num_shards = num_shards
        self._shards: List["OrderedDict[Any, Any]"] = [
            OrderedDict() for _ in range(num_shards)]
        self._seqs: List["OrderedDict[Any, int]"] = [
            OrderedDict() for _ in range(num_shards)]
        self._seq = itertools.count(1)
        self._ops = [0] * num_shards  # per-shard mutation counters

    @classmethod
    def from_mapping(cls, mapping, num_shards: int = DEFAULT_SHARDS,
                     name: str = "") -> "ShardedTable":
        """Wrap a plain dict (store restore / pre-shard snapshot),
        preserving its insertion order as the recency order."""
        table = cls(num_shards, name)
        for key, value in mapping.items():
            table[key] = value
        return table

    def shard_of(self, key) -> int:
        return shard_index(key, self.num_shards)

    # -- MutableMapping ------------------------------------------------

    def __getitem__(self, key):
        return self._shards[self.shard_of(key)][key]

    def __setitem__(self, key, value):
        i = self.shard_of(key)
        shard = self._shards[i]
        if key not in shard:
            self._seqs[i][key] = next(self._seq)
        shard[key] = value
        self._ops[i] += 1

    def __delitem__(self, key):
        i = self.shard_of(key)
        del self._shards[i][key]
        del self._seqs[i][key]
        self._ops[i] += 1

    def __contains__(self, key):
        return key in self._shards[self.shard_of(key)]

    def __len__(self):
        return sum(len(s) for s in self._shards)

    def __iter__(self) -> Iterator:
        for shard in self._shards:
            yield from shard

    def __repr__(self):
        return (f"ShardedTable({self.name or 'unnamed'}, "
                f"shards={self.num_shards}, len={len(self)})")

    # -- recency (the task-events table's contract) --------------------

    def iter_recent(self) -> Iterator:
        """Values newest-first: k-way merge of the per-shard insertion
        sequences (each shard's OrderedDict is already seq-ascending)."""
        lanes = [
            [(seq, key, i) for key, seq in reversed(s.items())]
            for i, s in enumerate(self._seqs)]
        iters = [iter(lane) for lane in lanes if lane]
        heads = [next(it) for it in iters]
        while heads:
            j = max(range(len(heads)), key=lambda k: heads[k][0])
            _, key, i = heads[j]
            yield self._shards[i][key]
            nxt = next(iters[j], None)
            if nxt is None:
                del heads[j], iters[j]
            else:
                heads[j] = nxt

    def popitem_oldest(self) -> Tuple[Any, Any]:
        """Evict the entry with the globally smallest insertion seq."""
        candidates = [(next(iter(s.values())), i)
                      for i, s in enumerate(self._seqs) if s]
        if not candidates:
            raise KeyError("popitem_oldest(): table is empty")
        _, i = min(candidates)
        key, _ = self._seqs[i].popitem(last=False)
        value = self._shards[i].pop(key)
        self._ops[i] += 1
        return key, value

    # -- observability -------------------------------------------------

    def shard_sizes(self) -> List[int]:
        return [len(s) for s in self._shards]

    def shard_ops(self) -> List[int]:
        return list(self._ops)

    def metrics_text(self) -> str:
        name = self.name or "table"
        lines = ["# TYPE gcs_table_shard_size gauge"]
        for i, n in enumerate(self.shard_sizes()):
            lines.append(
                f'gcs_table_shard_size{{table="{name}",shard="{i}"}} {n}')
        lines.append("# TYPE gcs_table_shard_ops counter")
        for i, n in enumerate(self._ops):
            lines.append(
                f'gcs_table_shard_ops{{table="{name}",shard="{i}"}} {n}')
        return "\n".join(lines) + "\n"

    # -- pickling (GCS snapshot) ---------------------------------------

    def __reduce__(self):
        items = [(s, k, self._shards[i][k])
                 for i, seqs in enumerate(self._seqs)
                 for k, s in seqs.items()]
        items.sort()  # global seq order → recency survives the snapshot
        return (_rebuild, (self.num_shards, self.name,
                           [(k, v) for _, k, v in items]))


def _rebuild(num_shards: int, name: str,
             items: List[Tuple[Any, Any]]) -> ShardedTable:
    table = ShardedTable(num_shards, name)
    for key, value in items:
        table[key] = value
    return table
