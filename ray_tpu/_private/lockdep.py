"""Runtime lock-order validator (the kernel's lockdep, in-process).

Under ``RAY_TPU_LOCKDEP=1`` (or a programmatic :func:`install`),
``threading.Lock`` / ``threading.RLock`` are replaced by tracked
wrappers. Every thread keeps the stack of locks it currently holds;
acquiring ``B`` while holding ``A`` records the directed edge ``A → B``
with the acquisition stacks of both ends (first witness wins). An edge
that closes a cycle in the global order graph — the classic ``A→B`` in
one thread, ``B→A`` in another — raises :class:`LockOrderError` in the
acquiring thread *before* the program can actually deadlock, and the
report carries both witness stacks. The chaos and object-store test
suites run with lockdep enabled (see tests/conftest.py) so every lock
refactor on the object plane is exercised against it.

Design notes:

* Edges are keyed per lock *instance*; every wrapper carries its
  allocation site (``file:line`` of construction) so reports name the
  lock the way a developer thinks of it. Instance keying trades recall
  (cross-instance ABBA on two locks of the same class is only caught
  when the same two instances witness both orders) for a near-zero
  false-positive rate — the right trade for a CI gate.
* RLock re-entrancy is not an edge: only the outermost acquisition of a
  recursive lock pushes onto the held stack.
* ``Condition.wait`` interop: the wrappers expose ``_release_save`` /
  ``_acquire_restore`` / ``_is_owned`` delegating to the real lock while
  keeping the held-stack bookkeeping exact across the wait window.
* The graph's own guard is a raw ``_thread.allocate_lock`` (never
  wrapped, never part of the order graph).

Activation: :func:`init_from_env` runs at ``ray_tpu`` import, so worker
daemons spawned with ``RAY_TPU_LOCKDEP=1`` in their environment
self-install, mirroring how the chaos plane activates per process.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

import _thread

ENV_VAR = "RAY_TPU_LOCKDEP"

_REAL_LOCK = _thread.allocate_lock
_REAL_RLOCK = _thread.RLock


class LockOrderError(RuntimeError):
    """A lock acquisition closed a cycle in the lock-order graph."""


class _Graph:
    """Global lock-order graph: nodes are live tracked locks, edges the
    observed held→acquired orderings with their first-witness stacks."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        # (id_a, id_b) -> (name_a, name_b, stack_ab) first witness of A→B
        self.edges: Dict[Tuple[int, int], Tuple[str, str, str]] = {}
        self.adj: Dict[int, Set[int]] = {}
        self.names: Dict[int, str] = {}
        self.cycles: List[str] = []
        # keep wrappers alive so ids can't be recycled into stale nodes
        self._pins: List[object] = []

    def note_lock(self, lock: "_TrackedLockBase") -> None:
        with self._mu:
            self.names[id(lock)] = lock._ld_name
            self._pins.append(lock)

    def add_edge(self, a: "_TrackedLockBase", b: "_TrackedLockBase",
                 stack_ab: str) -> Optional[str]:
        """Record A→B; return a cycle report iff it closes a cycle."""
        ka, kb = id(a), id(b)
        if ka == kb:
            return None
        with self._mu:
            if (ka, kb) in self.edges:
                return None
            path = self._path(kb, ka)
            self.edges[(ka, kb)] = (a._ld_name, b._ld_name, stack_ab)
            self.adj.setdefault(ka, set()).add(kb)
            if path is None:
                return None
            # cycle: B ->* A exists and we just added A -> B
            lines = [
                "lock-order cycle detected (potential deadlock):",
                f"  new edge: {a._ld_name} -> {b._ld_name}",
                "  acquired here:",
                _indent(stack_ab, "    "),
                "  conflicting prior ordering "
                f"({' -> '.join(self.names.get(k, '?') for k in path)}):",
            ]
            for ka2, kb2 in zip(path, path[1:]):
                _, _, st = self.edges[(ka2, kb2)]
                lines.append(
                    f"  edge {self.names.get(ka2, '?')} -> "
                    f"{self.names.get(kb2, '?')} acquired here:")
                lines.append(_indent(st, "    "))
            report = "\n".join(lines)
            self.cycles.append(report)
            return report

    def _path(self, src: int, dst: int) -> Optional[List[int]]:
        """Path src ->* dst in adj, or None. Caller holds self._mu."""
        if src == dst:
            return [src]
        seen = {src}
        stack = [(src, [src])]
        while stack:
            cur, path = stack.pop()
            for nxt in self.adj.get(cur, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


def _indent(text: str, pad: str) -> str:
    return "\n".join(pad + ln for ln in text.rstrip().splitlines())


def _site() -> str:
    """file:line of the nearest caller outside this module (the lock's
    allocation site)."""
    for f in reversed(traceback.extract_stack(limit=8)):
        if os.path.basename(f.filename) != "lockdep.py":
            return f"{os.path.basename(f.filename)}:{f.lineno}"
    return "<unknown>"


def _stack() -> str:
    return "".join(traceback.format_stack(limit=16)[:-3])


# per-thread held stack: list of [lock, recursion_count]
_tls = threading.local()

# Cross-thread view for hang diagnosis (health.dump_stacks): every
# thread's held list, keyed by ident, registered the first time the
# thread touches a tracked lock. Reads are best-effort snapshots — the
# lists mutate concurrently, but each mutation is a single list op, so
# a reader sees a coherent recent state, which is all a stack dump
# needs. Guarded by a raw lock (never part of the order graph).
_all_held: Dict[int, List[List[object]]] = {}
_all_held_mu = _REAL_LOCK()


def _held() -> List[List[object]]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
        with _all_held_mu:
            _all_held[threading.get_ident()] = h
    return h


def held_locks() -> Dict[int, List[str]]:
    """{thread_ident: [lock names]} of currently-held tracked locks
    across ALL threads. Dead threads are pruned as a side effect."""
    import sys

    alive = set(sys._current_frames())
    with _all_held_mu:
        dead = [ident for ident in _all_held if ident not in alive]
        for ident in dead:
            del _all_held[ident]
        items = [(ident, list(held)) for ident, held in _all_held.items()]
    out: Dict[int, List[str]] = {}
    for ident, held in items:
        names = []
        for entry in held:
            try:
                lock, count = entry
                name = lock._ld_name
            except Exception:  # noqa: BLE001 — entry mutated under us
                continue
            names.append(name if count <= 1
                         else f"{name} (depth {count})")
        if names:
            out[ident] = names
    return out


_GRAPH: Optional[_Graph] = None
_RAISE = True


def _note_acquired(lock: "_TrackedLockBase") -> None:
    graph = _GRAPH
    if graph is None:
        return
    held = _held()
    for entry in held:
        if entry[0] is lock:
            entry[1] += 1  # re-entrant: no new edge, no new frame
            return
    report = None
    if held:
        st = _stack()
        for entry in held:
            report = graph.add_edge(entry[0], lock, st) or report
    # push before raising so a caller that catches LockOrderError can
    # still release() coherently
    held.append([lock, 1])
    if report is not None and _RAISE:
        raise LockOrderError(report)


def _note_released(lock: "_TrackedLockBase", full: bool = False) -> None:
    if _GRAPH is None:
        return
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            if full:
                held[i][1] = 0
            else:
                held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return


class _TrackedLockBase:
    _ld_kind = "Lock"

    def __init__(self) -> None:
        self._ld_inner = self._make_inner()
        self._ld_name = (f"{self._ld_kind}@{_site()}"
                         f"#{id(self) & 0xffff:04x}")
        if _GRAPH is not None:
            _GRAPH.note_lock(self)

    def _make_inner(self):
        raise NotImplementedError

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._ld_inner.acquire(blocking, timeout)
        if got:
            _note_acquired(self)
        return got

    def release(self) -> None:
        self._ld_inner.release()
        _note_released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._ld_inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib (concurrent.futures.thread, threading internals) grabs
        # this off the lock for os.register_at_fork
        self._ld_inner._at_fork_reinit()
        _tls.__dict__.pop("held", None)
        # child is single-threaded here: parent threads' held lists are
        # meaningless (and their idents unreachable) — drop them
        _all_held.clear()

    def __repr__(self) -> str:
        return f"<tracked {self._ld_name} of {self._ld_inner!r}>"


class TrackedLock(_TrackedLockBase):
    _ld_kind = "Lock"

    def _make_inner(self):
        return _REAL_LOCK()

    # Condition-variable interop (threading.Condition picks these up when
    # present; the fallbacks it would synthesize skip our bookkeeping)
    def _release_save(self):
        self._ld_inner.release()
        _note_released(self, full=True)
        return None

    def _acquire_restore(self, _state) -> None:
        self._ld_inner.acquire()
        _note_acquired(self)

    def _is_owned(self) -> bool:
        # same heuristic CPython uses for non-recursive condition locks
        if self._ld_inner.acquire(False):
            self._ld_inner.release()
            return False
        return True


class TrackedRLock(_TrackedLockBase):
    _ld_kind = "RLock"

    def _make_inner(self):
        return _REAL_RLOCK()

    def release(self) -> None:
        self._ld_inner.release()
        _note_released(self)

    def _release_save(self):
        state = self._ld_inner._release_save()
        _note_released(self, full=True)
        return state

    def _acquire_restore(self, state) -> None:
        self._ld_inner._acquire_restore(state)
        _note_acquired(self)

    def _is_owned(self) -> bool:
        return self._ld_inner._is_owned()


def _lock_factory() -> TrackedLock:
    return TrackedLock()


def _rlock_factory() -> TrackedRLock:
    return TrackedRLock()


# ---------------------------------------------------------------------------
# install / inspect
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return _GRAPH is not None


def install(raise_on_cycle: bool = True) -> None:
    """Start tracking: new ``threading.Lock``/``RLock`` (and everything
    built on them — Condition, Event, Queue, …) join the order graph.
    Locks created before install() stay untracked."""
    global _GRAPH, _RAISE
    if _GRAPH is None:
        _GRAPH = _Graph()
    _RAISE = raise_on_cycle
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory


def uninstall() -> None:
    """Restore the real factories and drop the graph."""
    global _GRAPH
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _GRAPH = None
    held = getattr(_tls, "held", None)
    if held:
        # clear IN PLACE: the cross-thread registry aliases this list
        del held[:]


def cycle_reports() -> List[str]:
    """Cycle reports recorded so far (empty on a clean run)."""
    graph = _GRAPH
    return list(graph.cycles) if graph is not None else []


def edge_count() -> int:
    graph = _GRAPH
    if graph is None:
        return 0
    with graph._mu:
        return len(graph.edges)


def init_from_env() -> bool:
    """Install iff RAY_TPU_LOCKDEP=1 (called at ray_tpu import so every
    daemon process self-installs from its environment)."""
    if os.environ.get(ENV_VAR, "") in ("1", "true", "on"):
        install()
        return True
    return False
