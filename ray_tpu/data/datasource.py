"""Datasources & sinks.

Reference: `python/ray/data/datasource/` (~35 sources). Each datasource
yields `ReadTask`s — serializable zero-arg callables returning one block —
which the executor runs as ray_tpu tasks (reference
`datasource.py` ReadTask protocol).
"""

from __future__ import annotations

import glob as glob_mod
import json
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor

ReadTask = Callable[[], Block]


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in glob_mod.glob(os.path.join(p, "**", "*"),
                                         recursive=True)
                if os.path.isfile(f)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


class SimpleDatasource(Datasource):
    """Wrap a list of zero-arg read callables, one per partition —
    the minimal custom-source seam (reference: user Datasource
    subclasses, `python/ray/data/datasource/datasource.py`)."""

    def __init__(self, read_fns: List[ReadTask]):
        self._read_fns = list(read_fns)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return list(self._read_fns)


class RangeDatasource(Datasource):
    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n, shape = self.n, self.tensor_shape
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            lo, hi = i * chunk, min((i + 1) * chunk, n)
            if lo >= hi:
                break

            def read(lo=lo, hi=hi) -> Block:
                ids = np.arange(lo, hi, dtype=np.int64)
                if shape is None:
                    return {"id": ids}
                data = np.stack([np.full(shape, v, dtype=np.int64)
                                 for v in ids]) if hi > lo else \
                    np.zeros((0,) + shape, dtype=np.int64)
                return {"data": data}

            tasks.append(read)
        return tasks

    def estimate_inmemory_data_size(self):
        per = 8 if self.tensor_shape is None else \
            8 * int(np.prod(self.tensor_shape))
        return self.n * per


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        items = self.items
        n = len(items)
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for i in range(parallelism):
            part = items[i * chunk:(i + 1) * chunk]
            if not part:
                break
            tasks.append(lambda part=part: BlockAccessor.from_items(part))
        return tasks


class NumpyDatasource(Datasource):
    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(next(iter(self.arrays.values())))
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks = []
        for i in range(parallelism):
            part = {k: v[i * chunk:(i + 1) * chunk]
                    for k, v in self.arrays.items()}
            if not len(next(iter(part.values()))):
                break
            tasks.append(lambda part=part: part)
        return tasks


class _FileDatasource(Datasource):
    """One read task per file (reference FileBasedDatasource)."""

    def __init__(self, paths):
        self.paths = _expand_paths(paths)

    def _read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        read_file = self._read_file
        return [lambda p=p: read_file(p) for p in self.paths]


class CSVDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        import pandas as pd
        return BlockAccessor.from_pandas(pd.read_csv(path))


class JSONDatasource(_FileDatasource):
    """JSONL or a top-level JSON array per file."""

    def _read_file(self, path: str) -> Block:
        with open(path) as f:
            first = f.read(1)
            f.seek(0)
            if first == "[":
                rows = json.load(f)
            else:
                rows = [json.loads(line) for line in f if line.strip()]
        return BlockAccessor.from_rows(rows)


class ParquetDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq
        return BlockAccessor.from_arrow(pq.read_table(path))


class TextDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(_FileDatasource):
    def _read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        arr = np.empty(1, dtype=object)
        arr[0] = data
        return {"bytes": arr, "path": np.asarray([path], dtype=object)}


class ImageDatasource(_FileDatasource):
    def __init__(self, paths, size: Optional[tuple] = None,
                 mode: str = "RGB"):
        super().__init__(paths)
        self.size = size
        self.mode = mode

    def _read_file(self, path: str) -> Block:
        from PIL import Image
        img = Image.open(path).convert(self.mode)
        if self.size:
            img = img.resize(self.size)
        return {"image": np.expand_dims(np.asarray(img), 0),
                "path": np.asarray([path], dtype=object)}


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


def write_block_csv(block: Block, path: str) -> None:
    BlockAccessor(block).to_pandas().to_csv(path, index=False)


def write_block_json(block: Block, path: str) -> None:
    df = BlockAccessor(block).to_pandas()
    df.to_json(path, orient="records", lines=True)


def write_block_parquet(block: Block, path: str) -> None:
    import pyarrow.parquet as pq
    pq.write_table(BlockAccessor(block).to_arrow(), path)


class TFRecordDatasource(_FileDatasource):
    """TFRecord files of tf.train.Example protos, parsed with the
    dependency-free codec in `_tfrecord.py` (reference:
    `datasource/tfrecords_datasource.py`, which requires TensorFlow).
    Single-element lists flatten to scalar columns, matching the
    reference's auto-unwrap behavior. BytesList values stay bytes
    (as in the reference/TF — the wire cannot distinguish str from
    bytes, and arbitrary binary payloads like encoded images must not
    be UTF-8-decoded)."""

    def _read_file(self, path: str) -> Block:
        from ray_tpu.data import _tfrecord as tfr

        rows = []
        for payload in tfr.read_records(path):
            ex = tfr.parse_example(payload)
            row = {k: (v[0] if len(v) == 1 else
                       (np.asarray(v) if not isinstance(v[0], bytes)
                        else v))
                   for k, v in ex.items()}
            rows.append(row)
        return BlockAccessor.from_rows(rows)


def write_block_tfrecords(block: Block, path: str) -> None:
    from ray_tpu.data import _tfrecord as tfr

    acc = BlockAccessor(block)
    tfr.write_records(
        path, [tfr.build_example(acc.row(i))
               for i in range(acc.num_rows())])


class SQLDatasource(Datasource):
    """Rows from a DB-API connection (reference:
    `datasource/sql_datasource.py` — `read_sql(sql, connection_factory)`).
    One read task runs the query in a worker; the factory must be
    picklable (e.g. a module-level function opening sqlite3)."""

    def __init__(self, sql: str, connection_factory: Callable):
        self.sql = sql
        self.connection_factory = connection_factory

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        sql, factory = self.sql, self.connection_factory

        def read() -> Block:
            conn = factory()
            try:
                cur = conn.cursor()
                cur.execute(sql)
                names = [d[0] for d in cur.description]
                rows = cur.fetchall()
            finally:
                conn.close()
            return BlockAccessor.from_rows(
                [dict(zip(names, r)) for r in rows])

        return [read]


class ArrowDatasource(Datasource):
    """In-memory pyarrow Table(s), one block per table chunk."""

    def __init__(self, table):
        self.table = table

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        table = self.table
        n = table.num_rows
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = (n + parallelism - 1) // parallelism if n else 0
        tasks: List[ReadTask] = []
        for i in range(parallelism):
            lo = i * chunk
            hi = min(n, lo + chunk)
            if lo >= hi:
                break
            part = table.slice(lo, hi - lo)  # capture only the slice
            tasks.append(lambda part=part: BlockAccessor.from_arrow(part))
        return tasks


class WebDatasetDatasource(_FileDatasource):
    """WebDataset-style tar shards (reference:
    `datasource/webdataset_datasource.py`): each .tar member is named
    `<key>.<ext>`; members sharing a key form one sample, with columns
    named by extension. Pure-stdlib tarfile — no webdataset dependency.
    Text-ish extensions decode to str, `.json` parses, `.cls`/`.id`
    parse to int when possible; everything else stays bytes (encoded
    images etc. must not be UTF-8-decoded)."""

    _TEXT_EXTS = {"txt", "text", "caption", "transcript"}
    _INT_EXTS = {"cls", "id", "label", "index"}

    def _read_file(self, path: str) -> Block:
        import tarfile
        from collections import OrderedDict

        samples: "OrderedDict[str, dict]" = OrderedDict()
        with tarfile.open(path) as tar:
            for member in tar:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                key, _, ext = base.partition(".")
                data = tar.extractfile(member).read()
                row = samples.setdefault(key, {"__key__": key})
                ext = ext.lower()
                if ext == "json":
                    row[ext] = json.loads(data)
                elif ext in self._TEXT_EXTS:
                    row[ext] = data.decode("utf-8")
                elif ext in self._INT_EXTS:
                    try:
                        row[ext] = int(data.decode("utf-8").strip())
                    except ValueError:
                        row[ext] = data
                else:
                    row[ext] = data
        return BlockAccessor.from_rows(list(samples.values()))


def write_block_webdataset(block: Block, path: str) -> None:
    """One tar shard per block: each row becomes `<key>.<column>`
    members (key = row's __key__ or its index)."""
    import io
    import tarfile

    acc = BlockAccessor(block)
    with tarfile.open(path, "w") as tar:
        for i in range(acc.num_rows()):
            row = acc.row(i)
            key = str(row.get("__key__", i))
            for col, value in row.items():
                if col == "__key__":
                    continue
                if isinstance(value, bytes):
                    payload = value
                elif isinstance(value, str):
                    payload = value.encode("utf-8")
                elif isinstance(value, (dict, list)):
                    payload = json.dumps(value).encode("utf-8")
                else:
                    payload = str(value).encode("utf-8")
                info = tarfile.TarInfo(name=f"{key}.{col}")
                info.size = len(payload)
                tar.addfile(info, io.BytesIO(payload))
