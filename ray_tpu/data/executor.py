"""Streaming plan executor.

Reference: `python/ray/data/_internal/execution/streaming_executor.py:48` +
`streaming_executor_state.py:165` (pull-based OpState loop with
backpressure) and `_internal/planner/exchange/` (shuffle/sort exchanges).

Execution here is ray_tpu tasks over block refs with a bounded in-flight
window per stage (the ConcurrencyCap backpressure policy); all-to-all ops
(repartition/shuffle/sort/groupby) are two-stage map/reduce exchanges like
the reference's push-based shuffle.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext


# ---------------------------------------------------------------------------
# remote task bodies (module-level so they pickle by reference-by-value once)
# ---------------------------------------------------------------------------


def _run_read(read_task) -> Block:
    return read_task()


def _run_transform(transform, block: Block, idx: int = 0) -> Block:
    return transform(block, idx)


def _run_read_fused(read_task, transforms, idx: int) -> Block:
    """Read + fused map chain in ONE task: the intermediate blocks stay
    in this process (zero copies, no store round-trip)."""
    block = read_task()
    for t in transforms:
        block = t(block, idx)
    return block


def _count_rows(block: Block) -> int:
    return BlockAccessor(block).num_rows()


def _slice_block(block: Block, start: int, end: int) -> Block:
    return BlockAccessor(block).slice(start, end)


def _split_for_partition(block: Block, assign_fn, p: int) -> List[Block]:
    """Map side of an exchange: split one block into p partition pieces."""
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return [dict() for _ in range(p)]
    assignment = assign_fn(acc)
    return [acc.take(np.nonzero(assignment == i)[0]) for i in range(p)]


def _reduce_concat(*parts: Block) -> Block:
    return BlockAccessor.concat(list(parts))


# merge stage of the push-based exchange: folding a round's partition
# pieces (plus the running merged block) IS a concat
_merge_partials = _reduce_concat


def _reduce_shuffle(seed: Optional[int], part_idx: int = 0,
                    *parts: Block) -> Block:
    block = BlockAccessor.concat(list(parts))
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return block
    rng = np.random.default_rng(None if seed is None else seed + part_idx)
    return acc.take(rng.permutation(n))


def _reduce_sort(key: str, descending: bool, *parts: Block) -> Block:
    block = BlockAccessor.concat(list(parts))
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return block
    order = np.argsort(block[key], kind="stable")
    if descending:
        order = order[::-1]
    return acc.take(order)


def _sample_block(block: Block, key: str, k: int) -> np.ndarray:
    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return np.asarray([])
    idx = np.random.default_rng(0).choice(n, size=min(k, n), replace=False)
    return np.asarray(block[key])[idx]


_AGG_FNS = {
    "count": lambda v: len(v),
    "sum": lambda v: np.sum(v),
    "min": lambda v: np.min(v),
    "max": lambda v: np.max(v),
    "mean": lambda v: np.mean(v),
    "std": lambda v: np.std(v),
}


def _reduce_groupby(key: Optional[str], aggs, *parts: Block) -> Block:
    block = BlockAccessor.concat(list(parts))
    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return {}
    if key is None:
        row: Dict[str, Any] = {}
        for agg_name, on, out_name in aggs:
            vals = block[on] if on else next(iter(block.values()))
            row[out_name] = _AGG_FNS[agg_name](vals)
        return BlockAccessor.from_rows([row])
    keys = block[key]
    uniq = np.unique(keys)
    rows = []
    for kv in uniq:
        mask = keys == kv
        row = {key: kv}
        for agg_name, on, out_name in aggs:
            vals = (block[on] if on else keys)[mask]
            row[out_name] = _AGG_FNS[agg_name](vals)
        rows.append(row)
    return BlockAccessor.from_rows(rows)


def _zip_blocks(left: Block, right: Block) -> Block:
    nl = BlockAccessor(left).num_rows()
    nr = BlockAccessor(right).num_rows()
    if nl != nr:
        raise ValueError(
            f"zip requires equal rows per paired block ({nl} vs {nr}); "
            "repartition both datasets to aligned blocks first")
    out = dict(left)
    for k, v in right.items():
        out[k if k not in out else f"{k}_1"] = v
    return out


# ---------------------------------------------------------------------------
# executor
# ---------------------------------------------------------------------------


class _RemoteCache:
    """Lazily-created RemoteFunction wrappers (one GCS function push each)."""

    def __init__(self):
        self._cache: Dict[Tuple[Callable, int], Any] = {}

    def get(self, fn: Callable, num_returns: int = 1):
        key = (fn, num_returns)
        if key not in self._cache:
            rf = ray_tpu.remote(fn)
            if num_returns != 1:
                rf = rf.options(num_returns=num_returns)
            self._cache[key] = rf
        return self._cache[key]


class _MapWorker:
    """Pool actor hosting one warm UDF instance (reference
    `_internal/execution/operators/actor_pool_map_operator.py` _MapWorker).
    The transform factory runs in __init__, so a class UDF's state
    (tokenizer, decoder, model) is built once and reused per block."""

    def __init__(self, transform_factory):
        self._transform = transform_factory()

    def ready(self) -> bool:
        return True

    def apply(self, block: Block, idx: int) -> Block:
        return self._transform(block, idx)


class _ResourceBudget:
    """Admission budget for task submission (reference
    `_internal/execution/resource_manager.py:29` ResourceManager +
    backpressure policies): the in-flight task window is derived from the
    cluster's CPU count instead of a fixed constant, and submission
    additionally stalls while the local object store is above an
    occupancy threshold (completed-but-unconsumed blocks are filling it —
    producing more would only force spilling). At least one task may
    always run, so progress is guaranteed and consumption drains the
    store."""

    def __init__(self, ctx: DataContext):
        self.ctx = ctx
        self._cap: Optional[int] = None
        self._occ_checked = 0.0
        self._occ_high = False

    def task_cap(self) -> int:
        if self._cap is None:
            if self.ctx.max_concurrent_tasks is not None:
                # explicit user cap wins (and is the test knob)
                self._cap = max(1, self.ctx.max_concurrent_tasks)
            else:
                try:
                    cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
                except Exception:
                    cpus = 1.0
                # modest oversubscription hides push/reply latency
                self._cap = max(2, int(cpus * 1.5))
        return self._cap

    def store_pressure(self) -> bool:
        """True when the local shm arena is above the high-water mark.
        Rechecked at most every 0.25s (a stats() syscall per wait tick is
        wasteful)."""
        import time as _time
        now = _time.monotonic()
        if now - self._occ_checked < 0.25:
            return self._occ_high
        self._occ_checked = now
        self._occ_high = False
        try:
            from ray_tpu._private.object_ref import get_core_worker
            cw = get_core_worker()
            if cw is not None and cw.store is not None:
                st = cw.store.stats()
                if st["capacity"]:
                    # referenced (unevictable) bytes, not allocated: the
                    # arena may be full of evictable garbage a create
                    # would reclaim — stalling on that is a false stall
                    used = st.get("referenced", st["allocated"])
                    frac = used / st["capacity"]
                    self._occ_high = \
                        frac > self.ctx.store_backpressure_fraction
        except Exception:
            pass
        return self._occ_high


class StreamingExecutor:
    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        self._remote = _RemoteCache()
        self._budget = _ResourceBudget(self.ctx)

    # -- budgeted-window submission (the backpressure policy) --------------

    def _windowed(self, submit_fns: List[Callable[[], Any]]) -> List[Any]:
        budget = self._budget
        cap = budget.task_cap()
        out: List[Any] = [None] * len(submit_fns)
        in_flight: Dict[Any, int] = {}
        next_i = 0
        while next_i < len(submit_fns) or in_flight:
            while next_i < len(submit_fns) and len(in_flight) < cap:
                if in_flight and budget.store_pressure():
                    break  # drain before producing more blocks
                ref = submit_fns[next_i]()
                out[next_i] = ref
                # multi-return tasks yield a list; any one ref tracks
                # task completion for the backpressure window
                in_flight[ref[0] if isinstance(ref, list) else ref] = next_i
                next_i += 1
            if in_flight:
                ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                        timeout=30.0)
                for r in ready:
                    in_flight.pop(r, None)
        return out

    def _windowed_iter(self, fns) -> "Iterator[Any]":
        """Generator flavor of _windowed: pull submit thunks LAZILY from
        `fns` and yield each block ref as its task COMPLETES (completion
        order). Lazy pull means backpressure propagates up a chain of
        streaming stages; completion-order yield is what lets a split
        coordinator hand finished blocks to whichever consumer is
        hungriest (reference StreamingExecutor's pull-based loop,
        `streaming_executor_state.py:165`)."""
        budget = self._budget
        cap = budget.task_cap()
        fns = iter(fns)
        in_flight: Dict[Any, Any] = {}  # wait_ref -> yield_ref
        exhausted = False
        while not exhausted or in_flight:
            while not exhausted and len(in_flight) < cap:
                if in_flight and budget.store_pressure():
                    break
                try:
                    ref = next(fns)()
                except StopIteration:
                    exhausted = True
                    break
                in_flight[ref[0] if isinstance(ref, list) else ref] = ref
            if in_flight:
                ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                        timeout=30.0)
                for r in ready:
                    yield in_flight.pop(r)

    # -- plan walk ---------------------------------------------------------

    def execute(self, op: L.LogicalOp) -> List[Any]:
        """Returns the output block refs of the (optimized) plan."""
        op = L.optimize(op)
        return self._exec(op)

    def execute_iter(self, op: L.LogicalOp) -> "Iterator[Any]":
        """Streaming execution: yield output block refs as their tasks
        complete, while upstream stages keep producing — first blocks
        are consumable long before the pipeline finishes (the
        train-ingest hot path; reference `stream_split_iterator.py:32`).
        Barrier ops (shuffle/sort/groupby/...) and actor-pool stages
        fall back to full materialization of their subtree."""
        op = L.optimize(op)
        yield from self._iter(op)

    def _iter(self, op: L.LogicalOp) -> "Iterator[Any]":
        if isinstance(op, L.Read) and op.limit_rows is None:
            tasks = op.datasource.get_read_tasks(op.parallelism)
            rf = self._remote.get(_run_read)
            yield from self._windowed_iter(
                (lambda t=t: rf.remote(t)) for t in tasks)
        elif isinstance(op, L.FusedRead):
            tasks = op.datasource.get_read_tasks(op.parallelism)
            rf = self._remote.get(_run_read_fused)
            transforms = op.transforms
            yield from self._windowed_iter(
                (lambda t=t, i=i: rf.remote(t, transforms, i))
                for i, t in enumerate(tasks))
        elif isinstance(op, L.AbstractMap) and op.compute is None:
            transform = op.make_transform()
            rf = self._remote.get(_run_transform)
            upstream = self._iter(op.input_op)
            yield from self._windowed_iter(
                (lambda b=b, i=i: rf.remote(transform, b, i))
                for i, b in enumerate(upstream))
        else:
            yield from self._exec(op)

    def _exec(self, op: L.LogicalOp) -> List[Any]:
        if isinstance(op, L.InputBlocks):
            return list(op.block_refs)
        if isinstance(op, L.Read):
            if op.limit_rows is not None:
                return self._exec_read_limited(op)
            tasks = op.datasource.get_read_tasks(op.parallelism)
            rf = self._remote.get(_run_read)
            return self._windowed([
                (lambda t=t: rf.remote(t)) for t in tasks])
        if isinstance(op, L.FusedRead):
            tasks = op.datasource.get_read_tasks(op.parallelism)
            rf = self._remote.get(_run_read_fused)
            transforms = op.transforms
            return self._windowed([
                (lambda t=t, i=i: rf.remote(t, transforms, i))
                for i, t in enumerate(tasks)])
        if isinstance(op, L.AbstractMap):
            inputs = self._exec(op.input_op)
            if op.compute is not None:
                return self._exec_actor_map(op, inputs)
            transform = op.make_transform()
            rf = self._remote.get(_run_transform)
            return self._windowed([
                (lambda b=b, i=i: rf.remote(transform, b, i))
                for i, b in enumerate(inputs)])
        if isinstance(op, L.Limit):
            return self._exec_limit(op)
        if isinstance(op, L.Repartition):
            inputs = self._exec(op.input_op)
            return self._exchange(
                inputs, op.n, _round_robin_assigner(op.n), _reduce_concat)
        if isinstance(op, L.RandomShuffle):
            inputs = self._exec(op.input_op)
            p = self.ctx.shuffle_partitions or max(1, len(inputs))
            seed = op.seed
            return self._exchange(
                inputs, p, _random_assigner(p, seed),
                _reduce_shuffle, extra_args=lambda i: (seed, i))
        if isinstance(op, L.Sort):
            return self._exec_sort(op)
        if isinstance(op, L.GroupByAggregate):
            return self._exec_groupby(op)
        if isinstance(op, L.Union):
            out: List[Any] = []
            for child in op.inputs:
                out.extend(self._exec(child))
            return out
        if isinstance(op, L.Zip):
            left = self._exec(op.left)
            right = self._exec(op.right)
            if len(left) != len(right):
                raise ValueError(
                    f"zip requires equal block counts ({len(left)} vs "
                    f"{len(right)}); repartition first")
            rf = self._remote.get(_zip_blocks)
            return self._windowed([
                (lambda l=l, r=r: rf.remote(l, r))
                for l, r in zip(left, right)])
        raise TypeError(f"unknown logical op {op!r}")

    # -- actor-compute map stage -------------------------------------------

    def _exec_actor_map(self, op: L.AbstractMap,
                        inputs: List[Any]) -> List[Any]:
        """Run one map stage on a pool of warm UDF actors with autoscaling
        (reference `actor_pool_map_operator.py` + `_ActorPool`): blocks go
        to the least-loaded actor, each actor runs at most
        `max_tasks_in_flight_per_actor` blocks, and while there is a
        backlog with every actor saturated the pool grows up to
        `max_size`. The pool is torn down when the stage drains."""
        if not inputs:
            return []
        strategy = op.compute
        factory = op.make_transform_factory()
        actor_cls = ray_tpu.remote(_MapWorker)
        min_size = strategy.min_size
        max_size = strategy.max_size or min_size
        per_actor = max(1, strategy.max_tasks_in_flight_per_actor)
        budget = self._budget

        actors: List[Any] = []
        out: List[Any] = [None] * len(inputs)
        load: Dict[int, int] = {}
        ref_actor: Dict[Any, int] = {}
        next_i = 0
        # autoscaling trace, observable via the DataContext singleton
        # (the GCS-side ALIVE view lags worker spawn latency)
        stats = {"peak": 0, "grows": 0, "shrinks": 0}
        self.ctx.last_actor_pool_stats = stats
        killed: set = set()
        try:
            actors.extend(actor_cls.remote(factory)
                          for _ in range(min(min_size, len(inputs))))
            load.update({j: 0 for j in range(len(actors))})
            stats["peak"] = len(load)
            # block until at least one worker built its UDF state — a
            # broken constructor should fail the stage here, not
            # per-block (and the finally reaps the spawned pool)
            ray_tpu.get(actors[0].ready.remote(), timeout=300)
            while next_i < len(inputs) or ref_actor:
                while next_i < len(inputs):
                    if ref_actor and budget.store_pressure():
                        break  # drain output blocks before producing more
                    j = min(load, key=load.get)
                    if load[j] >= per_actor:
                        if len(actors) < max_size:
                            # backlog with every actor saturated: scale up
                            actors.append(actor_cls.remote(factory))
                            load[len(actors) - 1] = 0
                            stats["grows"] += 1
                            stats["peak"] = max(stats["peak"], len(load))
                            continue
                        break
                    ref = actors[j].apply.remote(inputs[next_i], next_i)
                    out[next_i] = ref
                    ref_actor[ref] = j
                    load[j] += 1
                    next_i += 1
                if ref_actor:
                    ready, _ = ray_tpu.wait(list(ref_actor),
                                            num_returns=1, timeout=30.0)
                    for r in ready:
                        j = ref_actor.pop(r, None)
                        if j is not None:
                            load[j] -= 1
                # scale down: an idle actor whose capacity the remaining
                # backlog no longer needs is released immediately
                # (reference `default_autoscaler.py` downscaling)
                remaining = (len(inputs) - next_i) + len(ref_actor)
                while len(load) > min_size:
                    idle = [j for j, n in load.items() if n == 0]
                    if not idle or remaining > (len(load) - 1) * per_actor:
                        break
                    # reap the NEWEST idle actor: it is the least warm,
                    # and on slow-spawning hosts may not even have
                    # scheduled yet — killing the oldest would discard a
                    # warm UDF while keeping a cold one
                    j = max(idle)
                    load.pop(j)
                    killed.add(j)
                    stats["shrinks"] += 1
                    try:
                        ray_tpu.kill(actors[j])
                    except Exception:
                        pass
        finally:
            # every spawned actor dies here, including ones spawned
            # before `load` was populated (a failed spawn loop must not
            # leak the warm UDF actors already created)
            for j, a in enumerate(actors):
                if j in killed:
                    continue
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        return out

    # -- all-to-all exchange (map: split into p, reduce: combine) ----------

    def _exchange(self, inputs: List[Any], p: int, assign_fn,
                  reduce_fn, extra_args=lambda i: ()) -> List[Any]:
        """Two-stage exchange. `reduce_fn(*extra_args(i), *parts)` combines
        partition i; one cached RemoteFunction serves all partitions."""
        if not inputs:
            return []
        rf = self._remote.get(reduce_fn)
        if p == 1:
            # degenerate exchange: one reduce over all input blocks
            return [rf.remote(*extra_args(0), *inputs)]
        if (self.ctx.use_push_based_shuffle
                and len(inputs) > self.ctx.shuffle_merge_factor):
            return self._exchange_push(inputs, p, assign_fn, reduce_fn,
                                       rf, extra_args)
        split_rf = self._remote.get(_split_for_partition, num_returns=p)
        cols = self._windowed([
            (lambda b=b: split_rf.remote(b, assign_fn, p)) for b in inputs])
        submit = []
        for i in range(p):
            parts_i = [cols[j][i] for j in range(len(inputs))]
            submit.append(lambda i=i, parts=parts_i:
                          rf.remote(*extra_args(i), *parts))
        return self._windowed(submit)

    def _exchange_push(self, inputs: List[Any], p: int, assign_fn,
                       reduce_fn, reduce_rf, extra_args) -> List[Any]:
        """Push-based (pipelined-merge) exchange.

        Reference: the push-based shuffle behind
        `DataContext.use_push_based_shuffle` (`python/ray/data/
        _internal/planner/exchange/push_based_shuffle_task_scheduler.py`)
        — instead of every reducer consuming one partial from EVERY map
        task (fan-in = num input blocks, all partials alive at once),
        map tasks run in rounds of `shuffle_merge_factor` and each
        round's partials are merged into a running per-partition block.
        Fan-in of any task is bounded by the merge factor + 1, partials
        die after their round's merge, and merging for round r overlaps
        the split tasks of round r+1 through the windowed submitter.
        """
        k = self.ctx.shuffle_merge_factor
        split_rf = self._remote.get(_split_for_partition, num_returns=p)
        merge_rf = self._remote.get(_merge_partials)
        merged: List[Any] = [None] * p
        for start in range(0, len(inputs), k):
            round_blocks = inputs[start:start + k]
            cols = self._windowed([
                (lambda b=b: split_rf.remote(b, assign_fn, p))
                for b in round_blocks])
            submit = []
            for i in range(p):
                parts = [cols[j][i] for j in range(len(round_blocks))]
                if merged[i] is not None:
                    parts = [merged[i]] + parts
                submit.append(lambda parts=parts: merge_rf.remote(*parts))
            merged = self._windowed(submit)
        if reduce_fn is _reduce_concat:
            # the merged blocks already ARE the concatenated partitions —
            # a final concat-of-one reduce would just re-copy everything
            return merged
        return self._windowed([
            (lambda i=i: reduce_rf.remote(*extra_args(i), merged[i]))
            for i in range(p)])

    def _exec_read_limited(self, op: L.Read) -> List[Any]:
        """Limit-pushdown read (reference `set_read_parallelism` /
        `limit_pushdown.py`): launch read tasks in small waves and STOP
        once enough rows exist — a `.limit(n)` over a big datasource
        must not fan out the whole read."""
        tasks = op.datasource.get_read_tasks(op.parallelism)
        rf = self._remote.get(_run_read)
        rf_count = self._remote.get(_count_rows)
        out: List[Any] = []
        rows = 0
        i = 0
        window = max(1, min(4, self._budget.task_cap()))
        in_flight: List[tuple] = []  # (block_ref, count_ref)
        while rows < op.limit_rows and (i < len(tasks) or in_flight):
            while i < len(tasks) and len(in_flight) < window:
                b = rf.remote(tasks[i])
                in_flight.append((b, rf_count.remote(b)))
                i += 1
            b, c = in_flight.pop(0)
            rows += ray_tpu.get(c, timeout=300)
            out.append(b)
        return out

    def _exec_limit(self, op: L.Limit) -> List[Any]:
        inputs = self._exec(op.input_op)
        rf_count = self._remote.get(_count_rows)
        rf_slice = self._remote.get(_slice_block)
        out: List[Any] = []
        remaining = op.n
        for b in inputs:
            if remaining <= 0:
                break
            n = ray_tpu.get(rf_count.remote(b), timeout=120)
            if n <= remaining:
                out.append(b)
                remaining -= n
            else:
                out.append(rf_slice.remote(b, 0, remaining))
                remaining = 0
        return out

    def _exec_sort(self, op: L.Sort) -> List[Any]:
        inputs = self._exec(op.input_op)
        if not inputs:
            return []
        p = max(1, len(inputs))
        key = op.key
        rf_sample = self._remote.get(_sample_block)
        samples = ray_tpu.get(
            [rf_sample.remote(b, key, 16) for b in inputs], timeout=300)
        allv = np.concatenate([s for s in samples if len(s)]) \
            if any(len(s) for s in samples) else np.asarray([0])
        qs = np.linspace(0, 100, p + 1)[1:-1]
        bounds = np.percentile(allv, qs) if len(qs) else np.asarray([])
        descending = op.descending
        refs = self._exchange(
            inputs, p, _range_assigner(key, bounds),
            _reduce_sort, extra_args=lambda i: (key, descending))
        # partitions ascend by range; for descending output reverse them
        return list(reversed(refs)) if descending else refs

    def _exec_groupby(self, op: L.GroupByAggregate) -> List[Any]:
        inputs = self._exec(op.input_op)
        if not inputs:
            return []
        key, aggs = op.key, op.aggs
        if key is None:
            rf = self._remote.get(_reduce_groupby)
            return [rf.remote(None, aggs, *inputs)]
        p = min(len(inputs), 8)
        return self._exchange(
            inputs, p, _hash_assigner(key, p),
            _reduce_groupby, extra_args=lambda i: (key, aggs))


# assigner factories (picklable closures shipped to map tasks)

def _round_robin_assigner(p: int):
    def assign(acc: BlockAccessor) -> np.ndarray:
        return np.arange(acc.num_rows()) % p
    return assign


def _random_assigner(p: int, seed: Optional[int]):
    def assign(acc: BlockAccessor) -> np.ndarray:
        rng = np.random.default_rng(seed)
        return rng.integers(0, p, size=acc.num_rows())
    return assign


def _range_assigner(key: str, bounds: np.ndarray):
    def assign(acc: BlockAccessor) -> np.ndarray:
        return np.searchsorted(bounds, acc.block[key], side="right")
    return assign


def _hash_assigner(key: str, p: int):
    def assign(acc: BlockAccessor) -> np.ndarray:
        vals = acc.block[key]
        # stable hash via string digest (object/str cols) or modulo (ints)
        if vals.dtype.kind in "iu":
            return vals % p
        import zlib
        return np.asarray([zlib.crc32(str(v).encode()) % p for v in vals])
    return assign
