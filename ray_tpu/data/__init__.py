"""ray_tpu.data — streaming distributed datasets.

Reference: `python/ray/data/` (SURVEY.md §2.4): lazy logical plan →
fusion optimizer → streaming execution over ray_tpu tasks, with columnar
numpy blocks (jax-ready) in the shared-memory object store.
"""

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data import logical as _L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.logical import ActorPoolStrategy
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.datasource import (
    ArrowDatasource,
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    SQLDatasource,
    TextDatasource,
    TFRecordDatasource,
)
from ray_tpu.data.iterator import DataIterator


def _default_parallelism() -> int:
    return DataContext.get_current().read_parallelism


def read_datasource(ds: Datasource,
                    parallelism: Optional[int] = None) -> Dataset:
    return Dataset(_L.Read(ds, parallelism or _default_parallelism()))


def range(n: int, *, parallelism: Optional[int] = None) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=shape),
                           parallelism)


def from_items(items: List[Any],
               parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(ItemsDatasource(items), parallelism)


def from_numpy(arrays, parallelism: Optional[int] = None) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return read_datasource(NumpyDatasource(arrays), parallelism)


def from_pandas(df, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(
        NumpyDatasource(BlockAccessor.from_pandas(df)), parallelism)


def read_csv(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(CSVDatasource(paths), parallelism)


def read_json(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(JSONDatasource(paths), parallelism)


def read_parquet(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(ParquetDatasource(paths), parallelism)


def read_text(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(TextDatasource(paths), parallelism)


def read_binary_files(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(BinaryDatasource(paths), parallelism)


def read_tfrecords(paths, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(TFRecordDatasource(paths), parallelism)


def read_webdataset(paths, parallelism: Optional[int] = None) -> Dataset:
    from ray_tpu.data.datasource import WebDatasetDatasource

    return read_datasource(WebDatasetDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory,
             parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(SQLDatasource(sql, connection_factory),
                           parallelism)


def from_arrow(table, parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(ArrowDatasource(table), parallelism)


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB",
                parallelism: Optional[int] = None) -> Dataset:
    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism)


__all__ = [
    "ActorPoolStrategy",
    "Block",
    "BlockAccessor",
    "DataContext",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "from_items",
    "from_numpy",
    "from_arrow",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_images",
    "read_json",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
