"""Dataset: the lazy, distributed dataset facade.

Reference: `python/ray/data/dataset.py:137` — transformations append
logical ops; execution happens at consumption (iteration, count, take,
write) through the streaming executor. `ExecutionPlan` here is simply the
logical-op chain plus a cached materialization
(reference `_internal/plan.py:37`).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.data import logical as L
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext
from ray_tpu.data.datasource import (
    write_block_csv,
    write_block_json,
    write_block_parquet,
    write_block_tfrecords,
)
from ray_tpu.data.executor import StreamingExecutor, _count_rows
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, op: L.LogicalOp):
        self._op = op
        self._materialized: Optional[List[Any]] = None

    # -- plan building (lazy) ----------------------------------------------

    def _derive(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(op)

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        return self._derive(L.MapRows(self._op, fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    fn_args: tuple = (),
                    fn_kwargs: Optional[dict] = None,
                    compute: Optional["L.ActorPoolStrategy"] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    ) -> "Dataset":
        """Batch transform. `fn` may be a callable class when
        `compute=ActorPoolStrategy(...)`: each pool actor instantiates it
        once (with `fn_constructor_args/kwargs`) and reuses it across
        blocks — warm stateful UDFs (reference
        `actor_pool_map_operator.py`)."""
        return self._derive(L.MapBatches(
            self._op, fn, batch_size, fn_args, fn_kwargs,
            compute=compute, fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        return self._derive(L.Filter(self._op, fn))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        return self._derive(L.FlatMap(self._op, fn))

    def add_column(self, col: str, fn: Callable) -> "Dataset":
        return self._derive(L.AddColumn(self._op, col, fn))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        return self._derive(L.DropColumns(self._op, cols))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._derive(L.SelectColumns(self._op, cols))

    def limit(self, n: int) -> "Dataset":
        return self._derive(L.Limit(self._op, n))

    def repartition(self, n: int) -> "Dataset":
        return self._derive(L.Repartition(self._op, n))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._derive(L.RandomShuffle(self._op, seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._derive(L.Sort(self._op, key, descending))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._derive(L.Union([self._op] + [o._op for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._derive(L.Zip(self._op, other._op))

    def _global_agg(self, kind: str, on: Optional[str]):
        label = f"{kind}({on or ''})"
        rows = self.groupby(None)._agg([(kind, on, label)]).take_all()
        return rows[0][label] if rows else None

    def sum(self, on: str):
        """Scalar column sum (reference `Dataset.sum`)."""
        return self._global_agg("sum", on)

    def min(self, on: str):
        return self._global_agg("min", on)

    def max(self, on: str):
        return self._global_agg("max", on)

    def mean(self, on: str):
        return self._global_agg("mean", on)

    def std(self, on: str):
        return self._global_agg("std", on)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column (reference `Dataset.unique`).
        Sorted when the values are orderable, else first-seen order."""
        out: Dict[Any, None] = {}
        for row in self.select_columns([column]).iter_rows():
            out.setdefault(row[column])
        try:
            return sorted(out)
        except TypeError:  # mixed / None values have no total order
            return list(out)

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # -- execution ---------------------------------------------------------

    def _execute(self) -> List[Any]:
        if self._materialized is None:
            self._materialized = StreamingExecutor().execute(self._op)
        return self._materialized

    def materialize(self) -> "Dataset":
        refs = self._execute()
        ds = Dataset(L.InputBlocks(refs))
        ds._materialized = refs
        return ds

    def num_blocks(self) -> int:
        return len(self._execute())

    def count(self) -> int:
        refs = self._execute()
        if not refs:
            return 0
        # fresh RemoteFunction per call: a cached one would hold a function
        # key from a previous cluster across shutdown()/init() cycles
        rf = ray_tpu.remote(_count_rows)
        return int(sum(ray_tpu.get([rf.remote(b) for b in refs],
                                   timeout=600)))

    def schema(self) -> Dict[str, str]:
        for block in DataIterator(self._execute())._iter_blocks():
            if BlockAccessor(block).num_rows():
                return BlockAccessor(block).schema()
        return {}

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for row in DataIterator(self._execute()).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return list(DataIterator(self._execute()).iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        return BlockAccessor(
            DataIterator(self._execute()).materialize_numpy()).to_pandas()

    def to_arrow(self):
        """Materialize as one pyarrow Table (reference
        `Dataset.to_arrow_refs` shape, collapsed to a local table)."""
        return BlockAccessor(
            DataIterator(self._execute()).materialize_numpy()).to_arrow()

    def to_numpy(self) -> Block:
        return DataIterator(self._execute()).materialize_numpy()

    # -- consumption -------------------------------------------------------

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        return DataIterator(self._execute()).iter_rows()

    def iter_batches(self, **kwargs) -> Iterator[Any]:
        return DataIterator(self._execute()).iter_batches(**kwargs)

    def iterator(self) -> DataIterator:
        return DataIterator(self._execute())

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference `Dataset.random_sample`):
        every row gets an independent draw (duplicate rows sample
        independently). Deterministic per (seed, partitioning) — the
        per-block RNG mixes the block's position in the dataset with the
        seed, so identical-content blocks still draw independent masks."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1]: {fraction}")
        base = int(np.random.default_rng(seed).integers(0, 2 ** 31))

        def sample_block(batch: Dict[str, Any],
                         block_idx: int) -> Dict[str, Any]:
            n = len(next(iter(batch.values()))) if batch else 0
            if n == 0:
                return batch
            mask = np.random.default_rng(
                (base, block_idx)).random(n) < fraction
            return {k: np.asarray(v)[mask] for k, v in batch.items()}

        return self._derive(L.MapBatches(
            self._op, sample_block, None, with_block_index=True))

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """(train, test) row split (reference `Dataset.train_test_split`:
        test gets the LAST `test_size` fraction of rows; pass
        shuffle=True to randomize first)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError(
                f"test_size must be in (0, 1): {test_size}")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        refs = ds._execute()
        ds = Dataset(L.InputBlocks(refs))
        ds._materialized = refs
        # one parallel count pass gives total + per-block sizes without
        # moving any block bytes to the driver
        rf_count = ray_tpu.remote(_count_rows)
        counts = ray_tpu.get([rf_count.remote(b) for b in refs],
                             timeout=600)
        total = int(sum(counts))
        n_test = int(total * test_size)
        train = ds.limit(total - n_test)
        # tail slice: skip the first total-n_test rows

        @ray_tpu.remote
        def _tail(block, skip):
            acc = BlockAccessor(block)
            return acc.slice(min(skip, acc.num_rows()), acc.num_rows())

        out, seen = [], 0
        cut = total - n_test
        for b, rows in zip(refs, counts):
            if seen + rows <= cut:
                pass  # entirely train
            elif seen >= cut:
                out.append(b)  # entirely test
            else:
                out.append(_tail.remote(b, cut - seen))
            seen += rows
        test = Dataset(L.InputBlocks(out))
        test._materialized = out
        return train, test

    def split(self, n: int) -> List["Dataset"]:
        """Split into n datasets by round-robin over blocks (reference
        `Dataset.split`). Repartitions first if fewer blocks than splits."""
        refs = self._execute()
        if len(refs) < n:
            # repartition the materialized blocks, not the original plan —
            # re-running the upstream pipeline would double all its work
            refs = Dataset(L.InputBlocks(refs)).repartition(n)._execute()
        shards = [refs[i::n] for i in range(n)]
        out = []
        for s in shards:
            ds = Dataset(L.InputBlocks(s))
            ds._materialized = s
            out.append(ds)
        return out

    def streaming_split(self, n: int) -> List[DataIterator]:
        """Per-train-worker iterators over ONE shared streaming
        execution (reference `StreamSplitDataIterator`,
        `stream_split_iterator.py:32`): a coordinator actor runs the
        plan in the background and hands each completed block to
        whichever consumer asks first — dynamically balanced (a slow
        worker gets fewer blocks), with first-block latency set by the
        first task, not the whole pipeline."""
        from ray_tpu.data.iterator import (_SplitCoordinator,
                                           StreamSplitDataIterator)

        if self._materialized is not None:
            op = L.InputBlocks(self._materialized)
        else:
            op = self._op
        coord_cls = ray_tpu.remote(_SplitCoordinator)
        coord = coord_cls.options(num_cpus=0).remote(op)
        return [StreamSplitDataIterator(coord) for _ in range(n)]

    # -- writes ------------------------------------------------------------

    def _write(self, path: str, ext: str, write_fn) -> List[str]:
        os.makedirs(path, exist_ok=True)
        refs = self._execute()
        rf = ray_tpu.remote(_make_writer(write_fn))
        outs = [os.path.join(path, f"part_{i:05d}.{ext}")
                for i in range(len(refs))]
        ray_tpu.get([rf.remote(b, p) for b, p in zip(refs, outs)],
                    timeout=600)
        return outs

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv", write_block_csv)

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json", write_block_json)

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet", write_block_parquet)

    def write_tfrecords(self, path: str) -> List[str]:
        return self._write(path, "tfrecords", write_block_tfrecords)

    def write_webdataset(self, path: str) -> List[str]:
        from ray_tpu.data.datasource import write_block_webdataset

        return self._write(path, "tar", write_block_webdataset)

    def __repr__(self) -> str:
        return f"Dataset(op={self._op.name})"


def _make_writer(write_fn):
    def write(block, path):
        write_fn(block, path)
        return path
    return write


class GroupedData:
    """Reference: `python/ray/data/grouped_data.py`."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def _agg(self, aggs: List[Tuple[str, Optional[str], str]]) -> Dataset:
        return self._ds._derive(
            L.GroupByAggregate(self._ds._op, self._key, aggs))

    def count(self) -> Dataset:
        return self._agg([("count", None, "count()")])

    def sum(self, on: str) -> Dataset:
        return self._agg([("sum", on, f"sum({on})")])

    def min(self, on: str) -> Dataset:
        return self._agg([("min", on, f"min({on})")])

    def max(self, on: str) -> Dataset:
        return self._agg([("max", on, f"max({on})")])

    def mean(self, on: str) -> Dataset:
        return self._agg([("mean", on, f"mean({on})")])

    def std(self, on: str) -> Dataset:
        return self._agg([("std", on, f"std({on})")])

    def aggregate(self, *aggs: Tuple[str, Optional[str], str]) -> Dataset:
        return self._agg(list(aggs))
