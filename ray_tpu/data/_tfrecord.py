"""TFRecord container + tf.train.Example wire codec, dependency-free.

Reference: `python/ray/data/datasource/tfrecords_datasource.py` — the
reference parses TFRecord files of tf.train.Example protos (via
TensorFlow). TensorFlow is not a dependency here, so this module
implements the two formats directly:

- TFRecord framing: per record `uint64 length | uint32 masked-crc32c of
  the length | payload | uint32 masked-crc32c of the payload`.
- tf.train.Example protobuf wire format (the 3-level message tree:
  Example{1: Features{1: map<string, Feature{1: BytesList | 2:
  FloatList | 3: Int64List}>}}), hand-coded varint/length-delimited
  parsing — a fixed, frozen schema, so a generic proto library buys
  nothing.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# --- crc32c (Castagnoli), table-driven -----------------------------------

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --- TFRecord framing -----------------------------------------------------

def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            data = f.read(length)
            f.read(4)  # data crc (not verified, reference-compatible)
            if len(data) < length:
                return
            yield data


def write_records(path: str, payloads: List[bytes]) -> None:
    with open(path, "wb") as f:
        for data in payloads:
            length = struct.pack("<Q", len(data))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


# --- minimal proto wire ---------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def _fields(buf: bytes) -> Iterator[tuple]:
    """Yield (field_number, wire_type, value) over one message."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            value, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            n, pos = _read_varint(buf, pos)
            value = buf[pos:pos + n]
            pos += n
        elif wire == 5:  # fixed32
            value = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:  # fixed64
            value = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


def parse_example(data: bytes) -> Dict[str, Any]:
    """tf.train.Example bytes -> {feature_name: list | np.ndarray}."""
    out: Dict[str, Any] = {}
    for field, _, features_buf in _fields(data):
        if field != 1:  # Example.features
            continue
        for f2, _, entry in _fields(features_buf):
            if f2 != 1:  # Features.feature map entry
                continue
            name, feature = None, b""
            for f3, _, v in _fields(entry):
                if f3 == 1:
                    name = v.decode()
                elif f3 == 2:
                    feature = v
            if name is None:
                continue
            for kind, wire, payload in _fields(feature):
                if kind == 1:  # BytesList
                    out[name] = [v for f4, _, v in _fields(payload)
                                 if f4 == 1]
                elif kind == 2:  # FloatList (packed fixed32)
                    vals = []
                    for f4, w4, v in _fields(payload):
                        if f4 != 1:
                            continue
                        if w4 == 2:  # packed
                            vals.extend(np.frombuffer(v, "<f4"))
                        else:
                            vals.append(
                                struct.unpack("<f", v)[0])
                    out[name] = np.asarray(vals, np.float32)
                elif kind == 3:  # Int64List (packed varint)
                    vals = []
                    for f4, w4, v in _fields(payload):
                        if f4 != 1:
                            continue
                        if w4 == 2:
                            pos = 0
                            while pos < len(v):
                                x, pos = _read_varint(v, pos)
                                vals.append(_to_signed(x))
                        else:
                            vals.append(_to_signed(v))
                    out[name] = np.asarray(vals, np.int64)
    return out


def _to_signed(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


def _delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, field << 3 | 2)
    _write_varint(out, len(payload))
    out.extend(payload)


def build_example(row: Dict[str, Any]) -> bytes:
    """{name: value(s)} -> tf.train.Example bytes. int -> Int64List,
    float -> FloatList, bytes/str -> BytesList."""
    features = bytearray()
    for name, value in row.items():
        vals = np.atleast_1d(np.asarray(value)) \
            if not isinstance(value, (bytes, str, list)) else (
                value if isinstance(value, list) else [value])
        feature = bytearray()
        first = vals[0]
        if isinstance(first, (bytes, str)):
            blist = bytearray()
            for v in vals:
                _delimited(blist, 1,
                           v.encode() if isinstance(v, str) else v)
            _delimited(feature, 1, bytes(blist))
        elif np.issubdtype(np.asarray(first).dtype, np.floating):
            packed = np.asarray(vals, "<f4").tobytes()
            flist = bytearray()
            _delimited(flist, 1, packed)
            _delimited(feature, 2, bytes(flist))
        else:
            body = bytearray()
            for v in vals:
                x = int(v)
                _write_varint(body, x + (1 << 64) if x < 0 else x)
            ilist = bytearray()
            _delimited(ilist, 1, bytes(body))
            _delimited(feature, 3, bytes(ilist))
        entry = bytearray()
        _delimited(entry, 1, name.encode())
        _delimited(entry, 2, bytes(feature))
        _delimited(features, 1, bytes(entry))
    example = bytearray()
    _delimited(example, 1, bytes(features))
    return bytes(example)
