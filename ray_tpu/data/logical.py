"""Logical plan operators + the fusion optimizer.

Reference: `python/ray/data/_internal/logical/{operators,rules,
optimizers.py}` — the key rule rebuilt here is **operator fusion**:
adjacent one-to-one transforms collapse into a single task per block
(reference `rules/operator_fusion.py`), which is also the XLA-ish thing to
do — fewer task launches, fewer object-store round trips.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    normalize_batch_output,
)
from ray_tpu.data.datasource import Datasource


@dataclasses.dataclass
class ActorPoolStrategy:
    """Run a map stage's UDF inside a pool of warm, stateful actors
    (reference `python/ray/data/_internal/compute.py` ActorPoolStrategy +
    `_internal/execution/operators/actor_pool_map_operator.py`).

    The pool starts at `min_size` and autoscales up to `max_size` while
    the stage has a backlog; each actor executes at most
    `max_tasks_in_flight_per_actor` blocks concurrently (pipelining the
    object transfer behind the running task). With a class UDF the class
    is instantiated ONCE per actor — expensive state (tokenizers, model
    weights, decoders) is paid per worker, not per block.
    """

    min_size: int = 1
    max_size: Optional[int] = None  # None: fixed pool of min_size
    max_tasks_in_flight_per_actor: int = 2

    def __post_init__(self):
        if self.min_size < 1:
            raise ValueError("min_size must be >= 1")
        if self.max_size is not None and self.max_size < self.min_size:
            raise ValueError("max_size must be >= min_size")


class LogicalOp:
    def __init__(self, input_op: Optional["LogicalOp"] = None):
        self.input_op = input_op

    @property
    def name(self) -> str:
        return type(self).__name__


class Read(LogicalOp):
    def __init__(self, datasource: Datasource, parallelism: int):
        super().__init__(None)
        self.datasource = datasource
        self.parallelism = parallelism
        # set by the limit-pushdown rule: the executor launches read
        # tasks incrementally and stops once this many rows exist
        self.limit_rows: Optional[int] = None


class InputBlocks(LogicalOp):
    """Already-materialized input (from_blocks / materialized datasets)."""

    def __init__(self, block_refs: List[Any]):
        super().__init__(None)
        self.block_refs = block_refs


class AbstractMap(LogicalOp):
    """One-to-one block transform; fusable (task-compute stages only).

    Transforms take ``(block, block_index)`` — the index is the block's
    position in the stage's input list, giving deterministic per-block
    identity to transforms that need it (e.g. ``random_sample``'s RNG).
    """

    #: ActorPoolStrategy for actor-compute stages; None = stateless tasks
    compute: Optional[ActorPoolStrategy] = None

    def make_transform(self) -> Callable[[Block, int], Block]:
        raise NotImplementedError

    def make_transform_factory(self) -> Callable[[], Callable]:
        """Picklable zero-arg factory producing the transform ON the
        executing actor (where class UDFs instantiate their state)."""
        t = self.make_transform()
        return lambda: t


class MapBatches(AbstractMap):
    def __init__(self, input_op, fn: Callable, batch_size: Optional[int],
                 fn_args: tuple = (), fn_kwargs: Optional[dict] = None,
                 with_block_index: bool = False,
                 compute: Optional[ActorPoolStrategy] = None,
                 fn_constructor_args: tuple = (),
                 fn_constructor_kwargs: Optional[dict] = None):
        super().__init__(input_op)
        self.fn = fn
        self.batch_size = batch_size
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs or {}
        self.with_block_index = with_block_index
        self.compute = compute
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs or {}
        if isinstance(fn, type) and compute is None:
            raise ValueError(
                "map_batches with a class UDF requires "
                "compute=ActorPoolStrategy(...) — the class is stateful "
                "and must live in pooled actors (reference semantics)")
        if not isinstance(fn, type) and (fn_constructor_args
                                         or fn_constructor_kwargs):
            raise ValueError(
                "fn_constructor_args/kwargs require a callable-class fn "
                "(they are passed to its __init__, once per pool actor)")

    @staticmethod
    def _batch_loop(fn, bs, args, kwargs, with_idx):
        def transform(block: Block, idx: int) -> Block:
            acc = BlockAccessor(block)
            n = acc.num_rows()
            if n == 0:
                return block
            size = bs or n
            outs = []
            for lo in range(0, n, size):
                batch = acc.slice(lo, min(lo + size, n))
                extra = (idx,) if with_idx else ()
                outs.append(normalize_batch_output(
                    fn(batch, *extra, *args, **kwargs)))
            return BlockAccessor.concat(outs)

        return transform

    def make_transform(self):
        if isinstance(self.fn, type):
            raise TypeError("class UDFs run via make_transform_factory "
                            "on actor compute")
        return self._batch_loop(self.fn, self.batch_size, self.fn_args,
                                self.fn_kwargs, self.with_block_index)

    def make_transform_factory(self):
        fn, bs = self.fn, self.batch_size
        args, kwargs = self.fn_args, self.fn_kwargs
        with_idx = self.with_block_index
        ctor_args, ctor_kwargs = (self.fn_constructor_args,
                                  self.fn_constructor_kwargs)
        batch_loop = MapBatches._batch_loop

        def factory():
            # class UDFs instantiate HERE — once per pool actor
            call = fn(*ctor_args, **ctor_kwargs) if isinstance(fn, type) \
                else fn
            return batch_loop(call, bs, args, kwargs, with_idx)

        return factory


class MapRows(AbstractMap):
    def __init__(self, input_op, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def make_transform(self):
        fn = self.fn

        def transform(block: Block, idx: int) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return BlockAccessor.from_rows(rows)

        return transform


class Filter(AbstractMap):
    def __init__(self, input_op, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def make_transform(self):
        fn = self.fn

        def transform(block: Block, idx: int) -> Block:
            acc = BlockAccessor(block)
            keep = np.asarray([bool(fn(r)) for r in acc.iter_rows()],
                              dtype=bool)
            return acc.take(np.nonzero(keep)[0]) if len(keep) else block

        return transform


class FlatMap(AbstractMap):
    def __init__(self, input_op, fn: Callable):
        super().__init__(input_op)
        self.fn = fn

    def make_transform(self):
        fn = self.fn

        def transform(block: Block, idx: int) -> Block:
            rows: List[dict] = []
            for r in BlockAccessor(block).iter_rows():
                rows.extend(fn(r))
            return BlockAccessor.from_rows(rows)

        return transform


class AddColumn(AbstractMap):
    def __init__(self, input_op, col: str, fn: Callable):
        super().__init__(input_op)
        self.col = col
        self.fn = fn

    def make_transform(self):
        col, fn = self.col, self.fn

        def transform(block: Block, idx: int) -> Block:
            out = dict(block)
            out[col] = np.asarray(fn(BlockAccessor(block)))
            return out

        return transform


class DropColumns(AbstractMap):
    def __init__(self, input_op, cols: List[str]):
        super().__init__(input_op)
        self.cols = cols

    def make_transform(self):
        cols = set(self.cols)
        return lambda block, idx: {k: v for k, v in block.items()
                                   if k not in cols}


class SelectColumns(AbstractMap):
    def __init__(self, input_op, cols: List[str]):
        super().__init__(input_op)
        self.cols = cols

    def make_transform(self):
        cols = list(self.cols)
        return lambda block, idx: {k: block[k] for k in cols}


class FusedMap(AbstractMap):
    """Fusion product: run several transforms in one task."""

    def __init__(self, input_op,
                 transforms: List[Callable[[Block, int], Block]],
                 fused_names: List[str]):
        super().__init__(input_op)
        self.transforms = transforms
        self.fused_names = fused_names

    @property
    def name(self) -> str:
        return "Fused[" + "->".join(self.fused_names) + "]"

    def make_transform(self):
        transforms = self.transforms

        def transform(block: Block, idx: int) -> Block:
            for t in transforms:
                block = t(block, idx)
            return block

        return transform


class FusedRead(LogicalOp):
    """Read fused with downstream map transforms: each read task
    produces its block AND runs the transform chain in the SAME task,
    so intermediate blocks never round-trip through the object store
    (reference `rules/zero_copy_map_fusion.py` + read-op fusion in
    `rules/operator_fusion.py` — one task wave instead of one per
    stage)."""

    def __init__(self, read: "Read",
                 transforms: List[Callable[[Block, int], Block]],
                 fused_names: List[str]):
        super().__init__(None)
        self.datasource = read.datasource
        self.parallelism = read.parallelism
        self.transforms = transforms
        self.fused_names = fused_names

    @property
    def name(self) -> str:
        return "Read->" + "->".join(self.fused_names)


class Limit(LogicalOp):
    def __init__(self, input_op, n: int):
        super().__init__(input_op)
        self.n = n


class Repartition(LogicalOp):
    def __init__(self, input_op, n: int):
        super().__init__(input_op)
        self.n = n


class RandomShuffle(LogicalOp):
    def __init__(self, input_op, seed: Optional[int] = None):
        super().__init__(input_op)
        self.seed = seed


class Sort(LogicalOp):
    def __init__(self, input_op, key: str, descending: bool = False):
        super().__init__(input_op)
        self.key = key
        self.descending = descending


class GroupByAggregate(LogicalOp):
    def __init__(self, input_op, key: Optional[str],
                 aggs: List[Tuple[str, Optional[str], str]]):
        """aggs: list of (agg_name, on_column, out_name)."""
        super().__init__(input_op)
        self.key = key
        self.aggs = aggs


class Union(LogicalOp):
    def __init__(self, inputs: List[LogicalOp]):
        super().__init__(None)
        self.inputs = inputs


class Zip(LogicalOp):
    def __init__(self, left: LogicalOp, right: LogicalOp):
        super().__init__(None)
        self.left = left
        self.right = right


# map ops that preserve row count 1:1 — Limit commutes past them
# (reference `rules/limit_pushdown.py`: only cardinality-preserving
# one-to-one ops; Filter/FlatMap/MapBatches can change row counts)
_CARDINALITY_PRESERVING = (MapRows, AddColumn, DropColumns, SelectColumns)


def _push_limit(op: "Limit") -> LogicalOp:
    """Limit pushdown (reference `rules/limit_pushdown.py`):
    - Limit(Limit(x, m), n) -> Limit(x, min(m, n))
    - Limit commutes below cardinality-preserving maps, so the map runs
      on only the surviving rows
    - Limit(Read) stays put but stamps `limit_rows` on the Read — the
      executor then launches read tasks incrementally instead of the
      whole wave (set_read_parallelism analogue)."""
    changed = True
    while changed:
        changed = False
        child = op.input_op
        if isinstance(child, Limit):
            op = Limit(child.input_op, min(op.n, child.n))
            changed = True
        elif (isinstance(child, _CARDINALITY_PRESERVING)
                and child.compute is None):
            inner = Limit(child.input_op, op.n)
            child.input_op = _push_limit(inner)
            return child
    if isinstance(op.input_op, Read):
        op.input_op.limit_rows = op.n
    return op


def clone_plan(op: LogicalOp) -> LogicalOp:
    """Per-node shallow copy of a plan tree. Datasets SHARE op objects
    (`Dataset._derive` wraps `self._op` without copying), so optimizer
    rules that rewire `input_op` or stamp fields must work on a private
    copy — mutating shared nodes would silently change the plans of
    sibling datasets."""
    import copy

    if not isinstance(op, LogicalOp):
        return op
    new = copy.copy(op)
    if isinstance(new, Union):
        new.inputs = [clone_plan(i) for i in new.inputs]
    elif isinstance(new, Zip):
        new.left = clone_plan(new.left)
        new.right = clone_plan(new.right)
    elif new.input_op is not None:
        new.input_op = clone_plan(new.input_op)
    return new


def optimize(op: LogicalOp) -> LogicalOp:
    """Bottom-up rules (reference `logical/rules/`): limit pushdown,
    then fusion of AbstractMap chains (`operator_fusion.py`). Operates
    on a private clone — the caller's plan is never mutated."""
    return _optimize(clone_plan(op))


def _optimize(op: LogicalOp) -> LogicalOp:
    if isinstance(op, Union):
        op.inputs = [_optimize(i) for i in op.inputs]
        return op
    if isinstance(op, Zip):
        op.left, op.right = _optimize(op.left), _optimize(op.right)
        return op
    if isinstance(op, Limit):
        op = _push_limit(op)
        if not isinstance(op, Limit):
            return _optimize(op)  # limit sank below a map: re-walk
    if op.input_op is not None:
        op.input_op = _optimize(op.input_op)
    if isinstance(op, AbstractMap) and isinstance(op.input_op, AbstractMap) \
            and op.compute is None and op.input_op.compute is None:
        # actor-compute stages never fuse: their UDF state lives in a
        # dedicated pool, and fusing a task-compute neighbor into it
        # would drag that neighbor's work onto the pool's actors
        # (reference fuses only compatible compute strategies)
        child = op.input_op
        child_transforms = (child.transforms
                            if isinstance(child, FusedMap)
                            else [child.make_transform()])
        child_names = (child.fused_names if isinstance(child, FusedMap)
                       else [child.name])
        op = FusedMap(
            child.input_op,
            child_transforms + [op.make_transform()],
            child_names + [op.name],
        )
    if isinstance(op, AbstractMap) and op.compute is None:
        # read fusion: the whole read->map chain becomes one task wave
        transforms = (op.transforms if isinstance(op, FusedMap)
                      else [op.make_transform()])
        names = (op.fused_names if isinstance(op, FusedMap)
                 else [op.name])
        child = op.input_op
        if isinstance(child, Read) and child.limit_rows is None:
            return FusedRead(child, transforms, names)
        if isinstance(child, FusedRead):
            # the input already fused into its read (bottom-up order);
            # append — the plan is a private clone, mutation is safe
            child.transforms = child.transforms + transforms
            child.fused_names = child.fused_names + names
            return child
    return op
