"""DataIterator: batched consumption of a set of block refs.

Reference: `python/ray/data/iterator.py:68,106` (`iter_batches`) and
`_internal/iterator/stream_split_iterator.py:32` (per-train-worker
splits). An iterator is picklable (block refs serialize), so train workers
can consume shards created by the driver.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


class DataIterator:
    def __init__(self, block_refs: List[Any]):
        self._block_refs = block_refs

    def _iter_blocks(self) -> Iterator[Block]:
        for ref in self._block_refs:
            yield ray_tpu.get(ref, timeout=600)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        """Yield dict-of-numpy (or pandas) batches of exactly batch_size
        (except possibly the last)."""
        carry: Optional[Block] = None
        rng = (np.random.default_rng(local_shuffle_seed)
               if local_shuffle_buffer_size else None)

        def emit(block: Block):
            if batch_format == "pandas":
                return BlockAccessor(block).to_pandas()
            return block

        def shuffled_blocks() -> Iterator[Block]:
            """Block stream, optionally re-chunked through a local
            shuffle buffer (reference local_shuffle_buffer_size)."""
            buf: List[Block] = []
            buf_rows = 0
            for block in self._iter_blocks():
                if not block or not BlockAccessor(block).num_rows():
                    continue
                if rng is None:
                    yield block
                    continue
                buf.append(block)
                buf_rows += BlockAccessor(block).num_rows()
                if buf_rows >= local_shuffle_buffer_size:
                    acc = BlockAccessor(BlockAccessor.concat(buf))
                    yield acc.take(rng.permutation(acc.num_rows()))
                    buf, buf_rows = [], 0
            if buf:
                acc = BlockAccessor(BlockAccessor.concat(buf))
                yield acc.take(rng.permutation(acc.num_rows()))

        for block in shuffled_blocks():
            if carry is not None:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor(block)
            n = acc.num_rows()
            lo = 0
            while n - lo >= batch_size:
                yield emit(acc.slice(lo, lo + batch_size))
                lo += batch_size
            if lo < n:
                carry = acc.slice(lo, n)
        if carry is not None and not drop_last:
            if BlockAccessor(carry).num_rows():
                yield emit(carry)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def materialize_numpy(self) -> Block:
        return BlockAccessor.concat(list(self._iter_blocks()))
