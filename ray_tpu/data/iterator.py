"""DataIterator: batched consumption of a set of block refs.

Reference: `python/ray/data/iterator.py:68,106` (`iter_batches`) and
`_internal/iterator/stream_split_iterator.py:32` (per-train-worker
splits). An iterator is picklable (block refs serialize), so train workers
can consume shards created by the driver.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu
from ray_tpu._private import fault_injection as _fi
from ray_tpu.data.block import Block, BlockAccessor


def _maybe_stall() -> None:
    # chaos plane: an active `data_stall` window makes every block read
    # sleep it out (models an ingest-source brownout)
    p = _fi._PLAN
    if p is not None:
        p.data_read_sync()


class DataIterator:
    def __init__(self, block_refs: List[Any]):
        self._block_refs = block_refs

    def _iter_blocks(self, prefetch: int = 0) -> Iterator[Block]:
        if prefetch <= 0:
            for ref in self._block_refs:
                _maybe_stall()
                yield ray_tpu.get(ref, timeout=600)
            return
        # Resolve up to `prefetch` blocks AHEAD of the consumer: the
        # fetch/deserialize of block i+1..i+P overlaps the caller's
        # compute on block i, so step wall-time approaches
        # max(fetch, compute) instead of their sum (reference
        # `iterator.py:109` prefetch_batches).
        from collections import deque

        window: deque = deque()
        refs = iter(self._block_refs)
        try:
            while True:
                while len(window) <= prefetch:
                    try:
                        window.append(next(refs).future())
                    except StopIteration:
                        break
                if not window:
                    return
                _maybe_stall()
                yield window.popleft().result(timeout=600)
        finally:
            for f in window:
                f.cancel()

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 1,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
        start_batch_index: int = 0,
    ) -> Iterator[Any]:
        """Yield dict-of-numpy (or pandas) batches of exactly batch_size
        (except possibly the last).

        `start_batch_index` resumes consumption mid-shard: the first
        `start_batch_index` batches (= `start_batch_index * batch_size`
        rows of the deterministic block stream) are skipped, so an
        elastic restore that persisted its read offset in the checkpoint
        continues exactly where the committed step left off — no batch
        duplicated, none skipped. Requires deterministic order
        (incompatible with local shuffle); exact only for iterators with
        a static block list (a `streaming_split` rebalances dynamically,
        so its offsets are best-effort counts, not content-stable)."""
        if start_batch_index < 0:
            raise ValueError("start_batch_index must be >= 0")
        if start_batch_index and local_shuffle_buffer_size:
            raise ValueError(
                "start_batch_index requires deterministic batch order; "
                "disable local_shuffle_buffer_size")
        skip_rows = start_batch_index * batch_size
        carry: Optional[Block] = None
        rng = (np.random.default_rng(local_shuffle_seed)
               if local_shuffle_buffer_size else None)

        def emit(block: Block):
            if batch_format == "pandas":
                return BlockAccessor(block).to_pandas()
            return block

        def shuffled_blocks() -> Iterator[Block]:
            """Block stream, optionally re-chunked through a local
            shuffle buffer (reference local_shuffle_buffer_size)."""
            buf: List[Block] = []
            buf_rows = 0
            for block in self._iter_blocks(prefetch=prefetch_batches):
                if not block or not BlockAccessor(block).num_rows():
                    continue
                if rng is None:
                    yield block
                    continue
                buf.append(block)
                buf_rows += BlockAccessor(block).num_rows()
                if buf_rows >= local_shuffle_buffer_size:
                    acc = BlockAccessor(BlockAccessor.concat(buf))
                    yield acc.take(rng.permutation(acc.num_rows()))
                    buf, buf_rows = [], 0
            if buf:
                acc = BlockAccessor(BlockAccessor.concat(buf))
                yield acc.take(rng.permutation(acc.num_rows()))

        for block in shuffled_blocks():
            if skip_rows:
                n_rows = BlockAccessor(block).num_rows()
                if skip_rows >= n_rows:
                    skip_rows -= n_rows
                    continue
                block = BlockAccessor(block).slice(skip_rows, n_rows)
                skip_rows = 0
            if carry is not None:
                block = BlockAccessor.concat([carry, block])
                carry = None
            acc = BlockAccessor(block)
            n = acc.num_rows()
            lo = 0
            while n - lo >= batch_size:
                yield emit(acc.slice(lo, lo + batch_size))
                lo += batch_size
            if lo < n:
                carry = acc.slice(lo, n)
        if carry is not None and not drop_last:
            if BlockAccessor(carry).num_rows():
                yield emit(carry)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def materialize_numpy(self) -> Block:
        return BlockAccessor.concat(list(self._iter_blocks()))


class _SplitCoordinator:
    """Actor owning one streaming execution of a dataset plan, feeding N
    consumers (reference `stream_split_iterator.py:32`
    SplitCoordinator). Blocks are handed out PULL-BASED: whichever
    consumer asks first gets the next completed block, so a slow train
    worker naturally receives fewer blocks while fast ones stay fed —
    dynamic balancing with no static assignment. Execution runs in a
    background thread pushing into a bounded queue, so the first blocks
    are consumable while upstream stages still produce (and the bound
    backpressures the pipeline against slow consumers)."""

    def __init__(self, op):
        import queue
        import threading

        from ray_tpu.data.executor import StreamingExecutor

        self._q: "queue.Queue" = queue.Queue(maxsize=16)
        self._error = None
        self._stopped = threading.Event()

        def run():
            try:
                for ref in StreamingExecutor().execute_iter(op):
                    while not self._stopped.is_set():
                        try:
                            self._q.put(ref, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                    if self._stopped.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — surfaced to consumers
                self._error = e
            finally:
                self._q.put(None)

        threading.Thread(target=run, daemon=True,
                         name="split-coordinator").start()

    def next_block(self):
        """Next completed block ref, or None when the stream ends."""
        item = self._q.get()
        if item is None:
            # poison-pill relay: wake every other blocked consumer
            self._q.put(None)
            if self._error is not None:
                raise self._error
            return None
        return item

    def stop(self):
        """Abandon the stream: the producer thread exits at its next
        put and remaining queued refs are dropped (consumers that
        stopped iterating early must call this via the iterator's
        `shutdown()` or the pipeline keeps producing into the queue)."""
        import queue

        self._stopped.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._q.put(None)
        return True


class StreamSplitDataIterator(DataIterator):
    """Per-train-worker view of a streaming split: pulls block refs from
    the shared coordinator on demand. Picklable (carries only the actor
    handle), so Train workers can consume a split created on the
    driver."""

    def __init__(self, coord):
        super().__init__([])
        self._coord = coord

    def shutdown(self):
        """Tear the SHARED coordinator down (all sibling split
        iterators stop receiving). Call when abandoning consumption
        early — e.g. between training epochs — so the coordinator's
        pipeline and actor don't linger for the session."""
        import ray_tpu

        try:
            ray_tpu.get(self._coord.stop.remote(), timeout=30)
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        try:
            ray_tpu.kill(self._coord)
        except Exception:  # noqa: BLE001
            pass

    def _iter_blocks(self, prefetch: int = 0) -> Iterator[Block]:
        from collections import deque

        # keep `prefetch`+1 next_block requests outstanding: the
        # coordinator round-trip AND the block fetch overlap consumer
        # compute
        pending: deque = deque()
        done = False
        while True:
            while not done and len(pending) <= max(0, prefetch):
                pending.append(self._coord.next_block.remote())
            if not pending:
                return
            _maybe_stall()
            ref = ray_tpu.get(pending.popleft(), timeout=600)
            if ref is None:
                done = True
                continue
            yield ray_tpu.get(ref, timeout=600)
