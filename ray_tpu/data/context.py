"""Global data-execution tunables (reference:
`python/ray/data/context.py:141` DataContext)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # concurrency cap for the streaming executor — the default
    # backpressure policy (reference ConcurrencyCapBackpressurePolicy)
    max_concurrent_tasks: int = 8
    default_batch_size: int = 1024
    read_parallelism: int = 8
    shuffle_partitions: Optional[int] = None
    eager_free: bool = True

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance
