"""Global data-execution tunables (reference:
`python/ray/data/context.py:141` DataContext)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # explicit concurrency cap for the streaming executor; None (default)
    # derives the in-flight window from cluster CPU count, and submission
    # additionally stalls while the object store is above
    # `store_backpressure_fraction` (reference ResourceManager budgets +
    # ConcurrencyCapBackpressurePolicy)
    max_concurrent_tasks: Optional[int] = None
    store_backpressure_fraction: float = 0.8
    default_batch_size: int = 1024
    read_parallelism: int = 8
    shuffle_partitions: Optional[int] = None
    # push-based shuffle (reference DataContext.use_push_based_shuffle /
    # the magnet-style pipelined shuffle): mappers' partials are merged
    # incrementally in rounds of `shuffle_merge_factor` blocks, so
    # reducer fan-in (and peak arg memory) is bounded by the merge
    # factor instead of the input block count. Engages automatically
    # when an exchange has more inputs than the merge factor.
    use_push_based_shuffle: bool = True
    shuffle_merge_factor: int = 8
    eager_free: bool = True
    # trace of the most recent actor-pool map stage's autoscaling
    # decisions ({"peak", "grows", "shrinks"}), written by the executor
    last_actor_pool_stats: Optional[dict] = None

    _instance = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance
