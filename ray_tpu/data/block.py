"""Blocks: the unit of data movement.

Reference: `python/ray/data/block.py:221` (`BlockAccessor` over Arrow
tables). TPU-first delta: the native block format is a **columnar dict of
numpy arrays** — exactly what feeds `jax.device_put` / `jnp.asarray` with
zero conversion — with Arrow/pandas as interop boundaries rather than the
core representation.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Dict[str, np.ndarray]


def _as_array(values: List[Any]) -> np.ndarray:
    # bytes columns must stay object-dtype: np.asarray would coerce
    # equal-length bytes to a fixed-width 'S' dtype, which silently
    # strips trailing NUL bytes on read-back — fatal for binary
    # payloads (encoded images etc.)
    if any(isinstance(v, bytes) for v in values):
        out = np.empty(len(values), dtype=object)
        out[:] = values
        return out
    try:
        return np.asarray(values)
    except ValueError:
        # ragged tensors / variable-length lists: keep an object array
        out = np.empty(len(values), dtype=object)
        out[:] = values
        return out


class BlockAccessor:
    """Uniform view over a columnar block."""

    def __init__(self, block: Block):
        self.block = block

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_rows(rows: List[Dict[str, Any]]) -> Block:
        if not rows:
            return {}
        # union of every row's keys (first-seen order): heterogeneous
        # rows (routine in e.g. webdataset shards) must not silently
        # drop columns absent from the first row; missing values are
        # None
        cols: Dict[str, List[Any]] = {}
        for r in rows:
            for k in r:
                cols.setdefault(k, [])
        for r in rows:
            for k in cols:
                cols[k].append(r.get(k))
        return {k: _as_array(v) for k, v in cols.items()}

    @staticmethod
    def from_items(items: List[Any]) -> Block:
        if items and isinstance(items[0], dict):
            return BlockAccessor.from_rows(items)
        return {"item": _as_array(items)}

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b and BlockAccessor(b).num_rows()]
        if not blocks:
            return {}
        keys = set(blocks[0].keys())
        for b in blocks[1:]:
            if set(b.keys()) != keys:
                raise ValueError(
                    f"cannot concat blocks with mismatched schemas: "
                    f"{sorted(keys)} vs {sorted(b.keys())}")
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}

    # -- introspection -----------------------------------------------------

    def num_rows(self) -> int:
        if not self.block:
            return 0
        return len(next(iter(self.block.values())))

    def size_bytes(self) -> int:
        return sum(a.nbytes if hasattr(a, "nbytes") else 64
                   for a in self.block.values())

    def schema(self) -> Dict[str, str]:
        return {k: str(v.dtype) for k, v in self.block.items()}

    # -- row/slice access --------------------------------------------------

    def row(self, i: int) -> Dict[str, Any]:
        return {k: v[i] for k, v in self.block.items()}

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self.num_rows()):
            yield self.row(i)

    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self.block.items()}

    def take(self, indices: np.ndarray) -> Block:
        return {k: v[indices] for k, v in self.block.items()}

    # -- interop -----------------------------------------------------------

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame({k: list(v) if v.dtype == object else v
                             for k, v in self.block.items()})

    def to_arrow(self):
        import pyarrow as pa
        return pa.table({k: pa.array(list(v)) if v.dtype == object
                         else pa.array(v) for k, v in self.block.items()})

    @staticmethod
    def from_pandas(df) -> Block:
        return {str(c): df[c].to_numpy() for c in df.columns}

    @staticmethod
    def from_arrow(table) -> Block:
        out: Block = {}
        for name in table.column_names:
            col = table.column(name)
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except Exception:
                out[name] = _as_array(col.to_pylist())
        return out


def normalize_batch_output(out: Any) -> Block:
    """map_batches outputs: dict-of-arrays, DataFrame, list of rows."""
    if isinstance(out, dict):
        arrs = {k: np.asarray(v) if not isinstance(v, np.ndarray) else v
                for k, v in out.items()}
        for k, v in arrs.items():
            if v.ndim == 0:
                raise TypeError(
                    f"map_batches output column {k!r} is a scalar; columns "
                    f"must be 1+-dimensional arrays/lists (wrap it: [{k}])")
        return arrs
    try:
        import pandas as pd
        if isinstance(out, pd.DataFrame):
            return BlockAccessor.from_pandas(out)
    except ImportError:
        pass
    if isinstance(out, builtins.list):
        return BlockAccessor.from_items(out)
    raise TypeError(f"invalid map_batches output type: {type(out)}")
